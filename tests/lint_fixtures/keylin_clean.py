"""Clean fixture: linear key discipline the key-linearity rule must
accept — re-bind chains, moves, disjoint-lane split contracts, and
branch-exclusive consumes. Zero findings, zero suppressions."""

import jax


def rebind_chain(key):
    key, sk = jax.random.split(key)
    x = jax.random.normal(sk, ())
    key, sk = jax.random.split(key)
    y = jax.random.normal(sk, ())
    return key, x + y


def key0_split_contract(keys):
    # The generate.py key0 contract: ONE equal-width split consumed on
    # disjoint constant lanes (advanced keys vs sample keys).
    next_keys = jax.random.split(keys, 2)[:, 0]
    subkeys = jax.random.split(keys, 2)[:, 1]
    return next_keys, subkeys


def linear_move(key):
    other = key  # a move: `key` is dead from here on
    return jax.random.normal(other, ())


def branch_exclusive(key, flag):
    # One consume per PATH is fine — the two sites never co-execute.
    if flag:
        return jax.random.bernoulli(key)
    return jax.random.normal(key, ())
