"""lock-discipline positive fixture: guarded fields touched bare.

`# expect: <rule>` comments mark the exact lines tests assert findings
on. This file is excluded from the repo self-lint (lint_fixtures/) and
is never imported.
"""

import threading


class Mailbox:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self.stats = {}  # unguarded on purpose: not annotated

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def size_racy(self):
        return len(self._items)  # expect: lock-discipline

    def close_racy(self):
        self._closed = True  # expect: lock-discipline

    def drain(self):
        out = []
        with self._lock:
            while self._items:
                out.append(self._items.pop())
        self.stats["drained"] = len(out)  # not annotated: no finding
        return out
