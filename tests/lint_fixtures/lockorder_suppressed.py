"""lock-order suppressed fixture: the inversion and the under-lock
hot dispatch are real, but each site carries a justified per-line
suppression — zero findings, nonzero suppressed count."""

from oryx_tpu.analysis.sanitizers import named_lock

# lock-order: one._lock < two._lock


class Engine:
    def __init__(self):
        self._one = named_lock("one._lock")
        self._two = named_lock("two._lock")

    def inverted_but_justified(self):
        # Fictional justification: startup-only path, single-threaded.
        with self._two:
            with self._one:  # oryxlint: disable=lock-order
                pass

    # hot-path
    def dispatch(self):
        return 1

    def locked_dispatch(self):
        with self._one:
            self.dispatch()  # oryxlint: disable=lock-order
