"""Suppressed fixture: a justified terminal-path escape — quiet but
counted by the suppression ratchet."""


class Engine:
    # obligations: _finalize_cost
    def _probe(self, req):
        if req is None:
            # Synthetic warmup probes have no ledger to finalize and
            # the ?state=done audit skips them by construction.
            return None  # oryxlint: disable=terminal-path
        return self._finalize_cost(None, req)
