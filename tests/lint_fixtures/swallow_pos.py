"""Positive fixture: broad swallows the swallowed-exception rule must
flag, with exact `# expect:` line markers."""

import logging

log = logging.getLogger(__name__)


def bare_pass():
    try:
        risky()
    except:  # noqa: E722  # expect: swallowed-exception
        pass


def broad_pass():
    try:
        risky()
    except Exception:  # expect: swallowed-exception
        pass


def broad_log_and_drop():
    try:
        risky()
    except Exception as e:  # expect: swallowed-exception
        log.warning("ignoring %s", e)


def broad_ellipsis_continue():
    for _ in range(3):
        try:
            risky()
        except BaseException:  # expect: swallowed-exception
            continue


def broad_in_tuple():
    try:
        risky()
    except (ValueError, Exception):  # expect: swallowed-exception
        print("oh well")


def broad_print_exc():
    import traceback

    try:
        risky()
    except Exception:  # expect: swallowed-exception
        traceback.print_exc()


def risky():
    raise ValueError("boom")
