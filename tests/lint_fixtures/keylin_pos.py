"""Positive fixture: PRNG-key linearity violations the key-linearity
rule must flag, with exact `# expect:` line markers. Keys are linear
values: one consume per binding, re-bind before the next."""

import jax


def double_draw(logits, key):
    first = jax.random.categorical(key, logits)
    second = jax.random.categorical(key, logits)  # expect: key-linearity
    return first, second


def split_then_reuse_parent(key):
    key2, sub = jax.random.split(key)
    noise = jax.random.normal(key, (4,))  # expect: key-linearity
    return key2, sub, noise


def consume_on_one_branch_then_join(key, flag):
    if flag:
        tok = jax.random.bernoulli(key)
    else:
        tok = 0
    extra = jax.random.bernoulli(key)  # expect: key-linearity
    return tok, extra


def loop_reuse(key, n):
    total = 0
    for _ in range(n):
        total = total + jax.random.bernoulli(key)  # expect: key-linearity
    return total


def same_lane_twice(keys):
    advanced = jax.random.split(keys, 2)[:, 0]
    again = jax.random.split(keys, 2)[:, 0]  # expect: key-linearity
    return advanced, again
