"""use-after-donate positive fixture: reads of consumed buffers.

Never imported; jax is referenced for realism only (the checker is
pure-AST)."""

from functools import partial

import jax


@partial(jax.jit, donate_argnames=("kv",))
def decode(params, kv, tok):
    return kv, tok + 1


eat_state = jax.jit(decode, donate_argnums=1)


def straight_line(params, kv, tok):
    kv2, tok = decode(params, kv, tok)
    return decode(params, kv, tok)  # expect: use-after-donate


def loop_wraparound(params, kv):
    out = None
    for i in range(4):
        out = decode(params, kv, i)  # expect: use-after-donate
    return out


def branch_merge(params, kv, flag):
    if flag:
        kv2, _ = decode(params, kv, 0)
    return kv.shape  # expect: use-after-donate


class Engine:
    def __init__(self, kv):
        self.kv = kv

    def step_stale(self, params):
        kv2, tok = decode(params, self.kv, 0)
        stale = self.kv["k"]  # expect: use-after-donate
        self.kv = kv2
        return stale, tok
