"""recompile-hazard positive fixture: tracer branches and unhashable
static operands."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("mode",))
def step(x, gate, *, mode):
    if gate:  # expect: recompile-hazard
        x = x + 1
    while gate > 0:  # expect: recompile-hazard
        x = x - 1
    if mode == "fast":  # static arg: fine
        x = x * 2
    if gate is None:  # identity test: fine
        x = x * 3
    if x.shape[0] > 2:  # shape is static under trace: fine
        x = x[:2]
    return x


@jax.jit
def bare(x, flag):
    return x if flag else -x  # expect: recompile-hazard


@partial(jax.jit, static_argnames=("pf_width",))
def ragged_step(tok, finished, *, pf_width):
    # The packed-buffer idiom hazard: locals DERIVED from traced
    # params are tracers too — branching Python on them recompiles (or
    # traces-errors) exactly like branching on the param itself.
    num_live = (~finished).sum()
    num_prefill = num_live + 1
    if num_live:  # expect: recompile-hazard
        tok = tok + 1
    while num_prefill > 0:  # expect: recompile-hazard
        tok = tok - 1
    if pf_width:  # static shape-class selector: fine
        tok = tok * 2
    rows = tok.shape[0]
    if rows > 4:  # derived from .shape only: static, fine
        tok = tok[:4]
    return tok


def caller(x):
    a = step(x, False, mode={"lr": 0.1})  # expect: recompile-hazard
    b = step(x, False, mode=f"bucket_{x.shape[0]}")  # expect: recompile-hazard
    c = step(x, False, mode="fast")  # hashable constant: fine
    return a, b, c
