"""Clean fixture: wall-clock reads that stay OUT of replay-critical
state — zero findings, zero suppressions."""

import time


class Engine:
    def _finish_step(self, step, rows):
        t0 = time.monotonic()
        self._dispatch(rows)
        # Timing feeds metrics only; the journal entry carries
        # deterministic facts. Field-granular taint: `self._dur`
        # being wall-clock does not poison `self` wholesale.
        self._dur = time.monotonic() - t0
        self.metrics.observe("step_seconds", self._dur)
        self.journal.append(build_journal_event(
            kind="step", step=step, rows=len(rows),
        ))

    # replay-decision
    def _select_fuse_k(self, live, replay_plan):
        # Replay consults the journaled plan; the live policy reads
        # only replay state (resident count), never the wall clock.
        if replay_plan is not None:
            return replay_plan.get(self.steps_run, 1)
        return 2 if len(live) == 1 else 1
