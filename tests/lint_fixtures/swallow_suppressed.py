"""Suppressed fixture: the ordinary oryxlint per-line suppression also
silences the rule (counted, never hidden)."""


def suppressed_swallow():
    try:
        risky()
    except Exception:  # oryxlint: disable=swallowed-exception
        pass


def risky():
    raise ValueError("boom")
