"""lock-discipline suppressed fixture: same shapes as lock_pos.py,
every escape carries a justification + suppression — zero findings."""

import threading


class Mailbox:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def size_estimate(self):
        # Monitoring-only read; len() on a list is atomic under the
        # GIL and an off-by-one snapshot is fine for a gauge.
        return len(self._items)  # oryxlint: disable=lock-discipline

    def close_from_signal_handler(self):
        # Signal handlers must not take locks; a torn bool is benign.
        self._closed = True  # oryxlint: disable=lock-discipline
