"""Positive fixture: annotated terminal paths that skip a declared
obligation, with exact `# expect:` line markers.

The first two shapes reproduce real bugs fixed by hand in PRs 5-7:
the PR 5 queue-depth-gauge leak (a queue pop path that skips the
gauge refresh, leaving /metrics claiming a deeper queue than exists)
and the PR 7 zero-resource-ledger bug (a cancelled-in-queue request
whose terminal path never finalizes its cost ledger, so the
?state=done audit and saturated-regime cost attribution miss it).
"""


class Engine:
    # PR 7 shape: cancelled-in-queue is still a terminal path — the
    # ledger (zero resources, real queue_s) and the wide event must
    # land even though the request never held a slot.
    # obligations: _finalize_cost, _emit_request_event
    def _cancel_queued(self, req):
        if req.handle.cancelled:
            req.trace.finish(cancelled=True)
            return  # expect: terminal-path
        cost = self._finalize_cost(None, req)
        req.trace.finish(cancelled=True, cost=cost)
        self._emit_request_event(req, status="cancelled")

    # PR 5 shape: EVERY pop must refresh the queue_depth gauge; the
    # early-continue cancel path skips it and the gauge goes stale.
    def _drain(self, msg):
        # obligations: _finalize_cost, queue_depth
        while self._queue:
            r = self._queue.popleft()
            if r.handle.cancelled:
                continue  # expect: terminal-path
            cost = self._finalize_cost(None, r)
            r.trace.finish(error=msg, cost=cost)
            self.metrics.set_gauge("queue_depth", len(self._queue))

    # A raise is an exit too: the slot must not leak on the error
    # path.
    # obligations: _clear_slot
    def _finish_error(self, s, msg):
        req = self.slots[s]
        if req is None:
            raise KeyError(s)  # expect: terminal-path
        self._clear_slot(s)

    # An except-handler return is an exit: a dispatch failure must
    # still finalize the ledger.
    # obligations: _finalize_cost
    def _step(self, req):
        try:
            self._dispatch(req)
        except RuntimeError:
            return None  # expect: terminal-path
        cost = self._finalize_cost(None, req)
        return cost

    # Falling off the end of the function is an exit: the guard makes
    # the discharge conditional, so the implicit exit misses it (the
    # finding anchors on the def).
    # obligations: _reset_pool
    def _recover(self, ok):  # expect: terminal-path
        if ok:
            self._reset_pool()
