"""host-sync positive fixture: implicit device→host syncs inside
functions marked `# hot-path`."""

import numpy as np

import jax


# hot-path
def decode_loop(arrays, lengths):
    total = 0.0
    for a in arrays:
        total += float(a)  # expect: host-sync
        host = np.asarray(a)  # expect: host-sync
        scalar = a.sum().item()  # expect: host-sync
        pulled = jax.device_get(a)  # expect: host-sync
        total += host.size + scalar + pulled.size
    return total, float("nan")  # literal cast: not a sync


def cold_path(a):
    # Not marked hot: the same calls are fine here.
    return float(a) + np.asarray(a).size
