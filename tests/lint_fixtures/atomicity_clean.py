"""atomicity clean fixture: the check and the act share one critical
section (and a read-only second block is fine) — zero findings, zero
suppressions."""

import threading
from collections import deque


class Sched:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = deque()  # guarded-by: _cond

    def check_and_act_atomically(self):
        with self._cond:
            if not self._queue:
                return
            self._queue.popleft()

    def read_only_after_check(self):
        with self._cond:
            if not self._queue:
                return
        with self._cond:
            return len(self._queue)
