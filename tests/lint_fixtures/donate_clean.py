"""use-after-donate clean fixture: the rebind-in-the-same-statement
idiom, in straight line, loops, and through attribute chains."""

from functools import partial

import jax


@partial(jax.jit, donate_argnames=("kv",))
def decode(params, kv, tok):
    return kv, tok + 1


def straight_line(params, kv, tok):
    kv, tok = decode(params, kv, tok)
    kv, tok = decode(params, kv, tok)
    return kv, tok


def loop(params, kv):
    tok = 0
    for _ in range(4):
        kv, tok = decode(params, kv, tok)
    return kv, tok


class Engine:
    def __init__(self, kv):
        self.kv = kv

    def step(self, params):
        kv2, tok = decode(params, self.kv, 0)
        self.kv = kv2
        return self.kv, tok
