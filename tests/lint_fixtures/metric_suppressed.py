"""metric-name suppressed fixture: plumbing-layer computed names and a
deliberate kind clash (testing the runtime rejection) justified."""


def passthrough(reg, name):
    # The abstraction layer itself: callers' literals are checked.
    return reg.counter(name)  # oryxlint: disable=metric-name


def runtime_rejection_test(reg):
    reg.counter("clash")  # oryxlint: disable=metric-name
    try:
        reg.gauge("clash")  # oryxlint: disable=metric-name
    except ValueError:
        pass


def emit_legacy_event(build_request_event):
    # A consumer still reading a pre-registry field name, migrated
    # deliberately: the suppression documents the debt.
    build_request_event(legacy_field=1)  # oryxlint: disable=metric-name
