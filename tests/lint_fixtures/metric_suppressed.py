"""metric-name suppressed fixture: plumbing-layer computed names and a
deliberate kind clash (testing the runtime rejection) justified."""


def passthrough(reg, name):
    # The abstraction layer itself: callers' literals are checked.
    return reg.counter(name)  # oryxlint: disable=metric-name


def runtime_rejection_test(reg):
    reg.counter("clash")  # oryxlint: disable=metric-name
    try:
        reg.gauge("clash")  # oryxlint: disable=metric-name
    except ValueError:
        pass
