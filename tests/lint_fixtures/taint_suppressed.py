"""Suppressed fixture: justified escapes for the replay-taint rule —
the counted `# oryxlint: disable=` form and the uncounted, tokenized
`# replay-exempt: <why>` form (which requires a nonempty reason)."""

import time


class Engine:
    def _stamp_recording(self, meta):
        # The header records when the RECORDING was made — a label for
        # humans, never read back by the replayer.
        wall = time.time()
        self.journal.stamp_header(meta, wall)  # oryxlint: disable=replay-taint

    def _debug_note(self, step):
        # replay-exempt: trace-only note, never read back by replay
        self.journal.append(build_journal_event(
            kind="note", step=step, ts_unix_s=time.monotonic(),
        ))
