"""Positive fixture: nondeterminism flowing into replay-critical
sinks (journal writes, marked decisions), with exact `# expect:`
line markers."""

import random
import time


class Engine:
    def _journal_step(self, step):
        stamp = time.monotonic()
        self.journal.append(build_journal_event(  # expect: replay-taint
            kind="step", step=step, ts_unix_s=stamp,
        ))

    def _pick_victim(self, slots):
        victim = random.randrange(len(slots))
        self.journal.append(build_journal_event(  # expect: replay-taint
            kind="evict", victim_request_id=victim,
        ))

    def _admit_order(self, ids):
        # Set iteration order is hash-seed-dependent: the journaled
        # admit order would differ between record and replay.
        for rid in set(ids):
            self.journal.append(build_journal_event(  # expect: replay-taint
                kind="admit", request_id=rid,
            ))

    def _stamp(self, req):
        # Field-granular: tainting req.admit_t taints exactly that
        # attribute, and journaling it is the finding.
        req.admit_t = time.monotonic()
        self.journal.append(build_journal_event(  # expect: replay-taint
            kind="admit", ts_unix_s=req.admit_t,
        ))

    # replay-decision
    def _select_fuse_k(self, live):
        jitter = time.monotonic_ns()
        return int(jitter) % 4  # expect: replay-taint
