"""use-after-donate suppressed fixture: deliberate reads (e.g. probing
deletion in a test helper) carry suppressions — zero findings."""

from functools import partial

import jax


@partial(jax.jit, donate_argnames=("kv",))
def decode(params, kv, tok):
    return kv, tok + 1


def probe_donation(params, kv):
    kv2, _ = decode(params, kv, 0)
    # This read is the POINT: asserting the buffer was consumed.
    return kv.is_deleted()  # oryxlint: disable=use-after-donate
