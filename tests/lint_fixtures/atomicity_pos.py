"""atomicity positive fixture: check-then-act on guarded fields with
the lock released between the check and the dependent mutation — the
early-exit shape and the escaped-local shape."""

import threading
from collections import deque


class Sched:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = deque()  # guarded-by: _cond

    def early_exit_check_then_act(self):
        with self._cond:
            if not self._queue:
                return
        prep = len("prompt prep outside the lock")
        with self._cond:
            self._queue.popleft()  # expect: atomicity
        return prep

    def escaped_guard(self):
        with self._cond:
            depth = len(self._queue)
        if depth > 4:
            with self._cond:
                self._queue.clear()  # expect: atomicity

    def fine_same_block(self):
        with self._cond:
            if self._queue:
                self._queue.popleft()
