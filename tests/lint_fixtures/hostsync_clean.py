"""host-sync clean fixture: hot path stays on device; host work uses
data that already crossed."""

import jax.numpy as jnp


# hot-path
def decode_loop(carry, steps):
    for _ in range(steps):
        carry = carry * 2 + jnp.sum(carry)
    return carry


def harvest(host_tokens):
    # Plain host-side work on host data: nothing to flag.
    return [int(t) for t in host_tokens]
