"""host-sync suppressed fixture: the deliberate once-per-chunk harvest
wrapped in an off/on region, plus a single-line escape."""

import numpy as np

import jax


# hot-path
def chunked_decode(chunks):
    out = []
    for c in chunks:
        # The harvest this loop exists to amortize — one sync per
        # chunk, not per token.
        # oryxlint: off=host-sync
        toks = np.asarray(c)
        done = bool(np.asarray(c).any())
        # oryxlint: on=host-sync
        out.append(toks)
        if done:
            break
    # TTFT metric needs one host scalar at the end.
    return out, jax.device_get(chunks[-1])  # oryxlint: disable=host-sync
