"""atomicity suppressed fixture: a single-consumer head pop — safe
for a structural reason the checker can't see, carrying the justified
per-line suppression that documents it."""

import threading
from collections import deque


class Sched:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = deque()  # guarded-by: _cond

    def single_consumer_pop(self):
        with self._cond:
            if not self._queue:
                return
        with self._cond:
            # This thread is the queue's only consumer: the head
            # peeked above cannot change between the blocks.
            self._queue.popleft()  # oryxlint: disable=atomicity
