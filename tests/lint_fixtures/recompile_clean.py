"""recompile-hazard clean fixture: device-side branching and hashable
static operands."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("mode",))
def step(x, gate, *, mode):
    x = jnp.where(gate, x + 1, x)
    if mode == "fast":
        x = x * 2
    return x


def caller(x, bucketed_mode):
    a = step(x, True, mode="fast")
    b = step(x, False, mode=bucketed_mode)
    return a, b


@partial(jax.jit, static_argnames=("pf_width",))
def ragged_step(tok, finished, *, pf_width):
    # Shape-derived locals and static-arg branches stay legal; traced
    # state is consumed with jnp.where, never Python control flow.
    rows = tok.shape[0]
    width = len(finished)
    if pf_width and rows > width:
        tok = tok[:width]
    live = jnp.where(finished, 0, 1)
    return tok + live.sum()
