"""recompile-hazard clean fixture: device-side branching and hashable
static operands."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("mode",))
def step(x, gate, *, mode):
    x = jnp.where(gate, x + 1, x)
    if mode == "fast":
        x = x * 2
    return x


def caller(x, bucketed_mode):
    a = step(x, True, mode="fast")
    b = step(x, False, mode=bucketed_mode)
    return a, b
