"""Clean fixture: every annotated exit discharges its obligations —
zero findings, zero suppressions."""


class Engine:
    # obligations: _finalize_cost, _emit_request_event
    def _reject_queued(self, req, msg):
        cost = self._finalize_cost(None, req)
        req.trace.finish(error=msg, cost=cost)
        self._emit_request_event(req, status="error")

    # A finally block discharges on EVERY path out — return, raise,
    # and fall-through all traverse it.
    # obligations: _clear_slot
    def _finish(self, s, req):
        try:
            return self._emit(req)
        finally:
            self._clear_slot(s)

    def _drain(self):
        # obligations: queue_depth
        while self._queue:
            self._queue.popleft()
            self.metrics.set_gauge("queue_depth", len(self._queue))

    # A `# discharges:` comment marks an indirect discharge the
    # checker can't see (the helper refreshes the gauge internally).
    # obligations: queue_depth
    def _drop_all(self):
        self._clear_queue_and_gauges()  # discharges: queue_depth
