"""Pallas kernel parity vs the XLA reference attention (interpret mode on
CPU; SURVEY.md §4 "Unit": Pallas kernel vs reference on fixed seeds)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu.ops.attention import attention as xla_attention
from oryx_tpu.ops.pallas.flash_attention import flash_attention
from oryx_tpu.ops.pallas.segment_attention import segment_attention


def _rand(key, shape):
    return jax.random.normal(key, shape, jnp.float32)


def _qkv(key, B, Tq, Tk, Hq, Hk, D):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        _rand(kq, (B, Tq, Hq, D)),
        _rand(kk, (B, Tk, Hk, D)),
        _rand(kv, (B, Tk, Hk, D)),
    )


@pytest.mark.parametrize("Tq,Tk", [(128, 128), (256, 256), (100, 100)])
def test_causal_matches_xla(Tq, Tk):
    q, k, v = _qkv(jax.random.key(0), 2, Tq, Tk, 4, 2, 32)
    ref = xla_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_noncausal_matches_xla():
    q, k, v = _qkv(jax.random.key(1), 1, 128, 128, 4, 4, 32)
    ref = xla_attention(q, k, v, causal=False)
    got = flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_kv_cache_decode_step():
    """Decode layout: Tq=1 with absolute positions into a longer cache."""
    B, S, Hq, Hk, D = 2, 160, 4, 2, 32
    q, k, v = _qkv(jax.random.key(2), B, 1, S, Hq, Hk, D)
    cur_len = jnp.asarray([100, 37], jnp.int32)
    q_pos = cur_len[:, None]
    kv_mask = (jnp.arange(S)[None, :] <= cur_len[:, None]).astype(jnp.int32)
    ref = xla_attention(
        q, k, v, causal=True, q_positions=q_pos, kv_mask=kv_mask
    )
    got = flash_attention(
        q, k, v, causal=True, q_positions=q_pos, kv_mask=kv_mask
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_prefill_with_padding_mask():
    B, T = 2, 96
    q, k, v = _qkv(jax.random.key(3), B, T, T, 4, 2, 32)
    lengths = jnp.asarray([96, 50], jnp.int32)
    kv_mask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    ref = xla_attention(
        q, k, v, causal=True, q_positions=pos, kv_positions=pos,
        kv_mask=kv_mask,
    )
    got = flash_attention(
        q, k, v, causal=True, q_positions=pos, kv_positions=pos,
        kv_mask=kv_mask,
    )
    # Compare only real rows; pad-row outputs are unspecified.
    for b, n in enumerate([96, 50]):
        np.testing.assert_allclose(
            np.asarray(got)[b, :n], np.asarray(ref)[b, :n], atol=2e-5
        )


def test_segment_attention_matches_xla():
    """Packed-ViT layout: several images in one buffer."""
    P, H, D = 256, 4, 32
    key = jax.random.key(4)
    q, k, v = _qkv(key, 1, P, P, H, H, D)
    seg = np.zeros(P, np.int32)
    seg[:60] = 1
    seg[60:200] = 2
    seg[200:230] = 3  # rest padding (0)
    seg = jnp.asarray(seg)[None]
    ref = xla_attention(q, k, v, q_segment_ids=seg, kv_segment_ids=seg)
    got = segment_attention(q, k, v, seg, seg)
    real = np.asarray(seg[0]) > 0
    np.testing.assert_allclose(
        np.asarray(got)[0, real], np.asarray(ref)[0, real], atol=2e-5
    )


def test_gradients_flow():
    """custom_vjp backward matches grad of the XLA reference."""
    q, k, v = _qkv(jax.random.key(5), 1, 64, 64, 4, 2, 16)

    def loss_pallas(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_gradients_multitile_gqa_mask(monkeypatch):
    """Pallas flash backward across MULTIPLE q/kv tiles (blocks patched
    small), with GQA group reduction and a padding mask."""
    from oryx_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(fa, "BLOCK_Q", 64)
    monkeypatch.setattr(fa, "BLOCK_K", 64)
    monkeypatch.setattr(fa, "BWD_BLOCK_Q", None)  # inherit 64x64 so the
    monkeypatch.setattr(fa, "BWD_BLOCK_K", None)  # backward stays multi-tile
    B, T = 2, 160
    q, k, v = _qkv(jax.random.key(6), B, T, T, 4, 2, 16)
    lengths = jnp.asarray([160, 90], jnp.int32)
    kv_mask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    qmask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.float32)

    def loss(attn):
        def f(q, k, v):
            o = attn(
                q, k, v, causal=True, q_positions=pos, kv_positions=pos,
                kv_mask=kv_mask,
            )
            # Only real rows contribute (pad-row outputs are unspecified).
            return jnp.sum((o * qmask[:, :, None, None]) ** 2)
        return f

    gp = jax.grad(loss(fa.flash_attention), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(xla_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
        )


def test_gradients_distinct_bwd_blocks(monkeypatch):
    """Backward tiling decoupled from forward tiling (ORYX_FLASH_BWD_*):
    fwd 64x64 tiles, bwd 128x32 — parity must hold across the remapped
    causal clamps and GQA reduction."""
    from oryx_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(fa, "BLOCK_Q", 64)
    monkeypatch.setattr(fa, "BLOCK_K", 64)
    monkeypatch.setattr(fa, "BWD_BLOCK_Q", 128)
    monkeypatch.setattr(fa, "BWD_BLOCK_K", 32)
    q, k, v = _qkv(jax.random.key(11), 2, 256, 256, 4, 2, 16)

    def loss(attn):
        def f(q, k, v):
            return jnp.sum(attn(q, k, v, causal=True) ** 2)
        return f

    gp = jax.grad(loss(fa.flash_attention), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(xla_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
        )
    # A bwd block that does not divide the padded length falls back to
    # the forward tiling rather than failing to lower.
    monkeypatch.setattr(fa, "BWD_BLOCK_K", 96)
    gp2 = jax.grad(loss(fa.flash_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp2, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
        )


def test_gradients_segments(monkeypatch):
    """Backward with segment ids (packed-ViT layout), non-causal."""
    from oryx_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(fa, "BLOCK_Q", 64)
    monkeypatch.setattr(fa, "BLOCK_K", 64)
    monkeypatch.setattr(fa, "BWD_BLOCK_Q", None)  # inherit 64x64 so the
    monkeypatch.setattr(fa, "BWD_BLOCK_K", None)  # backward stays multi-tile
    P, H, D = 128, 4, 16
    q, k, v = _qkv(jax.random.key(7), 1, P, P, H, H, D)
    seg = np.zeros(P, np.int32)
    seg[:50] = 1
    seg[50:100] = 2  # rest padding (0)
    seg = jnp.asarray(seg)[None]
    real = (np.asarray(seg[0]) > 0).astype(np.float32)
    rm = jnp.asarray(real)[None, :, None, None]

    def loss(attn, **kw):
        def f(q, k, v):
            o = attn(q, k, v, causal=False, **kw)
            return jnp.sum((o * rm) ** 2)
        return f

    gp = jax.grad(
        loss(fa.flash_attention, q_segment_ids=seg, kv_segment_ids=seg),
        argnums=(0, 1, 2),
    )(q, k, v)
    gx = jax.grad(
        loss(xla_attention, q_segment_ids=seg, kv_segment_ids=seg),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
        )


def test_qwen2_forward_pallas_impl_matches_xla():
    """Full decoder forward with attn_impl='pallas' == 'xla'."""
    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import qwen2

    cfg = cfg_lib.tiny_llm(vocab_size=128)
    params = qwen2.init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 33), 0, 128)
    ref, _ = qwen2.forward(params, cfg, input_ids=ids, attn_impl="xla")
    got, _ = qwen2.forward(params, cfg, input_ids=ids, attn_impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=3e-4)


def test_kv_cache_decode_multitile(monkeypatch):
    """Decode layout across MULTIPLE kv tiles: q positions are arbitrary
    (late in the cache) while kv positions are arange. Regression for the
    causal DMA-clamp bug: the prefill tile-index clamp must NOT apply when
    q positions aren't arange, or every kv tile aliases tile 0."""
    from oryx_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(fa, "BLOCK_Q", 64)
    monkeypatch.setattr(fa, "BLOCK_K", 64)
    monkeypatch.setattr(fa, "BWD_BLOCK_Q", None)  # inherit 64x64 so the
    monkeypatch.setattr(fa, "BWD_BLOCK_K", None)  # backward stays multi-tile
    B, S, Hq, Hk, D = 2, 512, 4, 2, 32
    q, k, v = _qkv(jax.random.key(11), B, 8, S, Hq, Hk, D)
    cur_len = jnp.asarray([400, 210], jnp.int32)
    q_pos = cur_len[:, None] + jnp.arange(8, dtype=jnp.int32)[None, :]
    kv_mask = (
        jnp.arange(S)[None, :] < (cur_len[:, None] + 8)
    ).astype(jnp.int32)
    ref = xla_attention(
        q, k, v, causal=True, q_positions=q_pos, kv_mask=kv_mask
    )
    got = flash_attention(
        q, k, v, causal=True, q_positions=q_pos, kv_mask=kv_mask
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_slot_positions_padded_prefill(monkeypatch):
    """slot_positions=True (training prefill layout: per-row arange
    positions, right-padded, kv_mask) must match XLA while enabling the
    causal tile skips — multi-tile to exercise the clamped index maps."""
    from oryx_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(fa, "BLOCK_Q", 64)
    monkeypatch.setattr(fa, "BLOCK_K", 64)
    monkeypatch.setattr(fa, "BWD_BLOCK_Q", None)  # inherit 64x64 so the
    monkeypatch.setattr(fa, "BWD_BLOCK_K", None)  # backward stays multi-tile
    B, T = 2, 256
    q, k, v = _qkv(jax.random.key(12), B, T, T, 4, 2, 32)
    lengths = jnp.asarray([256, 140], jnp.int32)
    kv_mask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.int32)
    # Per-row positions: arange on real slots, 0 on pads (build_mm_batch
    # layout) — position == slot index wherever valid.
    pos = jnp.where(
        jnp.arange(T)[None, :] < lengths[:, None],
        jnp.arange(T, dtype=jnp.int32)[None, :], 0,
    )
    ref = xla_attention(
        q, k, v, causal=True, q_positions=pos, kv_positions=pos,
        kv_mask=kv_mask,
    )
    got = flash_attention(
        q, k, v, causal=True, q_positions=pos, kv_positions=pos,
        kv_mask=kv_mask, slot_positions=True,
    )
    for b, n in enumerate([256, 140]):
        np.testing.assert_allclose(
            np.asarray(got)[b, :n], np.asarray(ref)[b, :n], atol=2e-5
        )

    # Gradients too: slot_positions reroutes BOTH backward kernels' skip
    # logic (dq run bound over zeroed pad positions; dkv program-id skip
    # with clamped q-side index maps). Pad rows masked out of the loss.
    qmask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.float32)

    def loss(attn, **extra):
        def f(q, k, v):
            o = attn(
                q, k, v, causal=True, q_positions=pos, kv_positions=pos,
                kv_mask=kv_mask, **extra,
            )
            return jnp.sum((o * qmask[:, :, None, None]) ** 2)
        return f

    gf = jax.grad(loss(flash_attention, slot_positions=True),
                  argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(xla_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        )
