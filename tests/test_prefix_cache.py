"""Shared-prefix KV cache + chunked prefill: allocator refcount/COW
lifecycle and guards, pool-geometry validation, radix-index unit tests,
generate-level chunked-prefill bit parity, and scheduler-level
cached-vs-cold bit parity (greedy + seeded sampling, COW splice,
eviction-then-readmit replay over shared pages), plus the cross-session
dense-cache plane."""

import logging
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import generate as gen_lib
from oryx_tpu.models import oryx, qwen2
from oryx_tpu.ops import paged_kv
from oryx_tpu.serve.pipeline import ChatSession, OryxInference
from oryx_tpu.serve.prefix_cache import (
    PagedPrefixCache,
    SessionPrefixCache,
    TokenTrie,
)
from oryx_tpu.serve.scheduler import ContinuousScheduler
from oryx_tpu.utils.metrics import ServingMetrics


class FakeTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


@pytest.fixture(scope="module")
def pipe():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    return OryxInference(FakeTokenizer(), params, cfg)


# ---------------------------------------------------------------------------
# Allocator: refcounts, share/release, guards, invariant
# ---------------------------------------------------------------------------


def test_allocator_refcount_lifecycle():
    a = paged_kv.PageAllocator(4, 8)
    p = a.alloc(2)
    assert [a.refcount(x) for x in p] == [1, 1]
    a.share(p)
    assert [a.refcount(x) for x in p] == [2, 2]
    a.free(p)  # one holder gone; pages stay allocated
    assert a.num_free == 2 and [a.refcount(x) for x in p] == [1, 1]
    a.release(p)  # last holder gone; pages return
    assert a.num_free == 4 and [a.refcount(x) for x in p] == [0, 0]


def test_allocator_double_free_and_share_guards_name_the_page():
    a = paged_kv.PageAllocator(4, 8)
    p = a.alloc(1)
    a.free(p)
    with pytest.raises(ValueError, match=f"double free of page {p[0]}"):
        a.free(p)
    with pytest.raises(ValueError, match=f"unallocated page {p[0]}"):
        a.share(p)
    q = a.alloc(1)[0]
    # One call dropping more references than the page holds fails BEFORE
    # mutating anything.
    with pytest.raises(ValueError, match=f"page {q}"):
        a.free([q, q])
    assert a.refcount(q) == 1
    with pytest.raises(ValueError, match="outside pool"):
        a.free([99])


def test_allocator_invariant_checker():
    a = paged_kv.PageAllocator(4, 8)
    p = a.alloc(2)
    a.share([p[0]])
    # Holders: one block table holding both pages, one cache holding p0.
    a.check_invariant([p, [p[0]]])
    with pytest.raises(RuntimeError, match="page"):
        a.check_invariant([p])  # p0's second reference unaccounted
    with pytest.raises(RuntimeError, match="page"):
        a.check_invariant([p, p])  # p1 double-held
    a.check_invariant()  # internal partition always checkable


# ---------------------------------------------------------------------------
# Pool geometry validation at engine construction
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kw", [
    {"page_size": 0}, {"num_slots": -1}, {"chunk": 0}, {"max_ctx": 0},
    {"num_pages": 0}, {"prefill_chunk": 0}, {"page_size": 2.5},
])
def test_engine_rejects_bad_geometry(pipe, kw):
    args = dict(num_slots=2, page_size=16, chunk=4, max_ctx=512,
                autostart=False)
    args.update(kw)
    with pytest.raises(ValueError):
        ContinuousScheduler(pipe, **args)


def test_engine_warns_when_pool_cannot_hold_max_ctx(pipe, caplog):
    with caplog.at_level(logging.WARNING, "oryx.serve.scheduler"):
        sched = ContinuousScheduler(
            pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
            num_pages=8, autostart=False,
        )
    sched.close()
    assert any("cannot hold one max_ctx" in r.message for r in caplog.records)


def test_oversized_prompt_rejected_with_actionable_message(pipe):
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        num_pages=4, autostart=False,
    )
    h = sched.submit({"question": "x" * 200}, 8)
    sched.start()
    with pytest.raises(RuntimeError, match="--num-pages"):
        h.result(timeout=600)
    sched.close()


# ---------------------------------------------------------------------------
# Radix index
# ---------------------------------------------------------------------------


def test_trie_longest_prefix_is_page_aligned():
    t = TokenTrie(4)
    toks = np.arange(11)
    path = t.extend(toks)
    assert len(path) == 2  # 11 tokens -> 2 full blocks, tail dropped
    assert len(t.walk(np.arange(11))) == 2
    assert len(t.walk(np.arange(7))) == 1  # only the first block matches
    assert len(t.walk(np.arange(3))) == 0  # shorter than one block
    div = np.concatenate([np.arange(4), [99, 98, 97, 96], np.arange(4)])
    assert len(t.walk(div)) == 1  # diverges at block 2


def test_paged_cache_insert_lookup_refcounts():
    alloc = paged_kv.PageAllocator(8, 4)
    cache = PagedPrefixCache(alloc)
    pages = alloc.alloc(3)
    toks = np.arange(13)  # 3 full blocks + 1 tail token
    assert cache.insert(toks, pages) == 3
    assert [alloc.refcount(p) for p in pages] == [2, 2, 2]
    alloc.free(pages)  # the "slot" releases; cache keeps them alive
    assert alloc.num_free == 5
    matched, got = cache.lookup(np.arange(20))
    assert matched == 12 and got == pages
    # Re-inserting an existing prefix is a no-op on references.
    dup = alloc.alloc(2)
    assert cache.insert(toks[:8], dup) == 0
    alloc.free(dup)
    alloc.check_invariant([cache.held_pages()])


def test_paged_cache_lru_eviction_skips_shared_pages():
    alloc = paged_kv.PageAllocator(8, 4)
    cache = PagedPrefixCache(alloc)
    a = alloc.alloc(2)
    cache.insert(np.arange(8), a)          # entry A (older)
    b = alloc.alloc(2)
    cache.insert(np.arange(100, 108), b)   # entry B (newer)
    alloc.free(a)
    # B's pages stay slot-shared (refcount 2): only A is reclaimable.
    assert cache.evict(4) == 2
    assert alloc.num_free == 4 + 2  # pool minus B's 2 pages
    matched, _ = cache.lookup(np.arange(8))
    assert matched == 0  # A is gone
    matched, _ = cache.lookup(np.arange(100, 108))
    assert matched == 8  # B survived
    # Touch order drives LRU: re-insert A, touch it, add C, evict one.
    a2 = alloc.alloc(2)
    cache.insert(np.arange(8), a2)
    alloc.free(a2)
    alloc.free(b)  # B now cache-only too
    cache.lookup(np.arange(8))  # A is most recent
    assert cache.evict(1) >= 1
    assert cache.lookup(np.arange(8))[0] == 8  # A survived (LRU was B)
    cache.clear()
    assert alloc.num_free == 8
    alloc.check_invariant([])


# ---------------------------------------------------------------------------
# Generate-level chunked prefill bit parity
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_llm():
    cfg = cfg_lib.tiny_llm(vocab_size=128)
    params = qwen2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _embed(params, ids):
    return params["embed"]["weight"][jnp.asarray(ids)]


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_generate_paged_prefill_chunked_bit_parity(tiny_llm, temperature):
    """generate_paged with prefill_chunk must emit BIT-identical tokens
    to the single-shot prefill — greedy and seeded sampling, mixed
    lengths, chunk boundaries that split rows unevenly."""
    cfg, params = tiny_llm
    gcfg = cfg_lib.GenerationConfig(
        temperature=temperature, top_p=0.9 if temperature else 1.0,
        eos_token_id=7,
    )
    rng = np.random.default_rng(3)
    ids = rng.integers(1, 128, size=(3, 32)).astype(np.int32)
    lengths = np.array([9, 21, 32], np.int32)
    common = dict(
        inputs_embeds=_embed(params, ids), lengths=lengths,
        max_new_tokens=8, page_size=8, chunk=4, kv_capacity=64,
        key=jax.random.key(11),
    )
    t0, n0, f0 = gen_lib.generate_paged(params, cfg, gcfg, **common)
    for pc in (5, 8, 16):
        t1, n1, f1 = gen_lib.generate_paged(
            params, cfg, gcfg, prefill_chunk=pc, **common
        )
        np.testing.assert_array_equal(np.asarray(t0), np.asarray(t1))
        np.testing.assert_array_equal(np.asarray(n0), np.asarray(n1))
        np.testing.assert_array_equal(np.asarray(f0), np.asarray(f1))


# ---------------------------------------------------------------------------
# Scheduler-level cached-vs-cold parity
# ---------------------------------------------------------------------------

SYS = (
    "You are a meticulous multimodal assistant. Always answer with "
    "care, cite what you see, and keep replies short. "
)


def _run_all(sched, reqs):
    handles = [
        sched.submit({"question": q}, cap, sampling)
        for q, cap, sampling in reqs
    ]
    sched.start()
    results = [h.result(timeout=600) for h in handles]
    sched.close()
    return handles, results


def test_cached_prefix_decode_matches_cold_greedy(pipe):
    """The acceptance bar: a request admitted over a cached prefix
    (pages spliced, only the suffix prefilled) produces the exact reply
    of the cold path — and of the dense solo pipeline."""
    q1, q2 = SYS + "what is shown?", SYS + "what happens next?"
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    handles, results = _run_all(
        sched, [(q1, 6, None), (q2, 6, None), (q1, 6, None)]
    )
    for q, (reply, _, _) in zip((q1, q2, q1), results):
        assert reply == pipe.chat(q, max_new_tokens=6), q
    # The shared SYS prefix really was served from the cache.
    assert metrics.get("prefix_cache_hit_tokens_total") >= 2 * (
        len(SYS) // 16 * 16 - 16
    )
    assert metrics.get("prefix_cache_entries") >= 1
    sched._check_pool_invariant()


def test_cached_prefix_seeded_sampling_matches_cold(pipe):
    """Sampling draws depend only on the request's own key and the
    (bit-identical) logits, so a seeded sampled request must reproduce
    across cold and cached admissions."""
    q = SYS + "tell me a story"
    sampling = {"temperature": 0.9, "top_p": 0.9, "seed": 5}
    replies = []
    for prefix_cache in (False, True):
        sched = ContinuousScheduler(
            pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
            autostart=False, prefix_cache=prefix_cache,
        )
        # Two in a row: with the cache on, the second admission splices
        # the first's donated prompt pages.
        _, results = _run_all(
            sched, [(q, 6, dict(sampling)), (q, 6, dict(sampling))]
        )
        replies.append([r[0] for r in results])
    assert replies[0][0] == replies[0][1]  # deterministic replay, cold
    assert replies[0] == replies[1]  # cached == cold, both requests


def test_cow_splice_on_page_aligned_prompt(pipe):
    """When the cache covers the ENTIRE prompt, admission must keep one
    token to prefill — the write lands mid-page in a shared page, which
    triggers the copy-on-write splice. Craft a page-aligned prompt and
    demand bit-equal replies plus a mid-page hit count."""
    ps = 16
    base = SYS + "describe it"
    L = len(pipe._prepare_request({"question": base})[0])
    q = base + "x" * ((-L) % ps)  # pad until the prompt is page-aligned
    L = len(pipe._prepare_request({"question": q})[0])
    assert L % ps == 0
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    handles, results = _run_all(sched, [(q, 6, None), (q, 6, None)])
    assert results[0][0] == results[1][0] == pipe.chat(q, max_new_tokens=6)
    # Second admission matched the whole prompt, clamped to L-1 — a
    # mid-page splice (hit count not a page multiple) proves COW ran.
    assert metrics.get("prefix_cache_hit_tokens_total") == L - 1
    sched._check_pool_invariant()


def test_chunked_prefill_interleaves_and_matches(pipe):
    """Admission prefill bounded at prefill_chunk tokens per engine
    step: replies still match the solo pipeline bit-for-bit and the
    chunk-size histogram shows multiple bounded dispatches."""
    long_q = SYS * 3 + "summarize everything above"
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=1024,
        metrics=metrics, autostart=False, prefill_chunk=64,
    )
    reqs = [("hello there", 8, None), (long_q, 6, None)]
    handles, results = _run_all(sched, reqs)
    for (q, cap, _), (reply, _, _) in zip(reqs, results):
        assert reply == pipe.chat(q, max_new_tokens=cap), q
    L = len(pipe._prepare_request({"question": long_q})[0])
    assert L > 64  # the long prompt genuinely needed several chunks
    fam = metrics.registry.existing("prefill_chunk_tokens")
    hist = fam._children[()]
    assert hist.total >= math.ceil(L / 64) + 1
    assert metrics.get("prefill_tokens_total") >= L
    sched._check_pool_invariant()


def test_eviction_readmit_replay_with_shared_pages(pipe):
    """Page pressure with the cache holding shared pages: the younger
    slot evicts, re-admits over the (still cached) prefix, replays
    deterministically, and the pool invariant balances afterwards."""
    q1, q2 = SYS + "first question here", SYS + "second question here"
    chunk, ps = 4, 16
    row1 = np.asarray(pipe._prepare_request({"question": q1})[0])
    row2 = np.asarray(pipe._prepare_request({"question": q2})[0])
    ids1, ids2 = len(row1), len(row2)
    m = min(ids1, ids2)
    neq = row1[:m] != row2[:m]
    shared_full = (int(np.argmax(neq)) if neq.any() else m) // ps
    admit1 = math.ceil((ids1 + chunk) / ps)
    admit2 = math.ceil((ids2 + chunk) / ps)
    cap = (admit1 * ps - ids1) + ps  # force one growth page per row
    metrics = ServingMetrics()
    # Pool sized WITH sharing in mind: the second admission splices
    # `shared_full` pages instead of allocating them, so pressure needs
    # that many fewer pages to materialize.
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=chunk, max_ctx=512,
        num_pages=admit1 + admit2 - shared_full + 1, metrics=metrics,
        autostart=False,
    )
    handles, results = _run_all(sched, [(q1, cap, None), (q2, cap, None)])
    assert metrics.get("evicted") >= 1
    for q, (reply, reason, usage) in zip((q1, q2), results):
        assert reply == pipe.chat(q, max_new_tokens=cap), q
        assert usage[1] == cap
    sched._check_pool_invariant()


def test_max_tokens_1_finish_donates_only_written_kv(pipe):
    """Regression: a max_tokens=1 request finishes at ACTIVATION — its
    tok0 is emitted but never fed back, so its KV slot holds prefill pad
    garbage. Finish-time donation must cap at the device-confirmed KV
    length, or a page-boundary at prompt+1 poisons the cache."""
    ps = 16
    base = SYS + "one token please"
    L = len(pipe._prepare_request({"question": base})[0])
    q = base + "y" * ((-(L + 1)) % ps)  # (L+1) page-aligned
    L = len(pipe._prepare_request({"question": q})[0])
    assert (L + 1) % ps == 0
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=4, max_ctx=512,
        autostart=False,
    )
    handles, results = _run_all(sched, [(q, 1, None)])
    assert results[0][2][1] == 1  # completion_tokens
    # Only the PROMPT's full pages may be cached — the (L+1)-token
    # boundary would include the never-written tok0 slot.
    assert sched.prefix_cache.pages == L // ps
    sched._check_pool_invariant()


def test_session_cache_drops_unreachable_displaced_states(pipe):
    """Regression: every turn's stream extends the last, shadowing its
    whole trie path — the superseded state must be dropped immediately,
    not pinned (a dense HBM cache) until LRU rotation."""
    shared = SessionPrefixCache(block_size=16, capacity=4)
    s = ChatSession(pipe, shared=shared)
    s.ask(SYS + "turn one", max_new_tokens=4)
    assert shared.entries == 1
    s.ask("turn two", max_new_tokens=4)
    # Turn 2's path covers turn 1's entirely: exactly one state remains.
    assert shared.entries == 1


def test_prefix_cache_metrics_families_render(pipe):
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    text = metrics.render()
    for fam in (
        "oryx_serving_prefix_cache_hit_tokens_total",
        "oryx_serving_prefix_cache_miss_tokens_total",
        "oryx_serving_prefix_cache_evicted_pages_total",
        "oryx_serving_prefix_cache_entries",
        "oryx_serving_prefix_cache_pages",
        "oryx_serving_prefill_tokens_total",
        "oryx_serving_prefill_chunk_tokens_bucket",
    ):
        assert fam in text, fam
    sched.close()


# ---------------------------------------------------------------------------
# Cross-session dense-cache plane
# ---------------------------------------------------------------------------


def test_session_prefix_cache_cross_session_reuse(pipe):
    """Two fresh ChatSessions sharing the pipe-level index: the second
    session's first ask reuses the first's donated state and still
    answers exactly like an uncached session."""
    shared = SessionPrefixCache(block_size=16, capacity=2)
    q = SYS + "what do you see?"
    s1 = ChatSession(pipe, shared=shared)
    r1 = s1.ask(q, max_new_tokens=6)
    assert shared.entries == 1
    s2 = ChatSession(pipe, shared=shared)
    probe = shared.lookup(
        np.asarray(pipe._prepare_request({"question": q})[0], np.int64)
    )
    assert probe is not None  # the donated state is reachable
    r2 = s2.ask(SYS + "anything else?", max_new_tokens=6)
    plain = ChatSession(pipe, cache=False)
    assert r1 == plain.ask(q, max_new_tokens=6)
    plain2 = ChatSession(pipe, cache=False)
    assert r2 == plain2.ask(SYS + "anything else?", max_new_tokens=6)
    # A STREAMED session seeds from the shared index too and yields the
    # identical reply.
    s_stream = ChatSession(pipe, shared=shared)
    streamed = "".join(s_stream.ask_stream(q, max_new_tokens=6))
    assert streamed == r1
    # Capacity bound: a third distinct conversation evicts the LRU.
    s3 = ChatSession(pipe, shared=shared)
    s3.ask("totally different " * 3, max_new_tokens=4)
    assert shared.entries <= 2


def test_session_cache_media_fingerprint_guard(pipe):
    """Text states must never seed an image session and vice versa: the
    media fingerprint roots the trie."""
    shared = SessionPrefixCache(block_size=16, capacity=4)
    s1 = ChatSession(pipe, shared=shared)
    s1.ask(SYS + "hello", max_new_tokens=4)
    img = (np.random.default_rng(0).integers(
        0, 255, size=(64, 64, 3)
    ).astype(np.uint8))
    ids = pipe._prepare_request({"question": SYS + "hello"})[0]
    assert shared.lookup(
        np.asarray(ids, np.int64),
        media_key=(((64, 64, 3), 123),),
    ) is None
