"""moment_dtype knob (config.TrainConfig): bf16 first moment halves the
m buffer; the variance buffer must stay fp32 regardless."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import oryx
from oryx_tpu.train import step as step_lib
from oryx_tpu.train.optimizer import make_optimizer

from tests.test_trainer_modes import _batch


def _moment_leaves(opt_state):
    """All (mu_leaf, nu_leaf) arrays inside a ScaleByAdamState tree."""
    mus, nus = [], []
    for s in jax.tree.leaves(
        opt_state, is_leaf=lambda x: hasattr(x, "mu") and hasattr(x, "nu")
    ):
        if hasattr(s, "mu"):
            mus.extend(jax.tree.leaves(s.mu))
            nus.extend(jax.tree.leaves(s.nu))
    return mus, nus


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_moment_dtype_applied_and_step_trains(dtype):
    base = cfg_lib.oryx_tiny()
    cfg = dataclasses.replace(
        base, train=dataclasses.replace(base.train, moment_dtype=dtype)
    )
    params = oryx.init_params(cfg, jax.random.key(0))
    tx = make_optimizer(cfg.train, params)
    opt_state = tx.init(params)

    mus, nus = _moment_leaves(opt_state)
    assert mus and nus
    assert all(m.dtype == jnp.dtype(dtype) for m in mus), (
        {m.dtype for m in mus}
    )
    assert all(n.dtype == jnp.float32 for n in nus), {n.dtype for n in nus}

    params0 = jax.tree.map(np.asarray, params)  # train_step donates params
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state
    )
    batch = {k: jnp.asarray(v)[None] for k, v in _batch(cfg).items()}
    losses = []
    for _ in range(3):
        state, metrics = step_lib.train_step(state, batch, cfg, tx)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all(), losses
    # Params must actually move under the bf16 moments.
    moved = jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - np.asarray(b)))),
        params0, state.params,
    )
    assert max(jax.tree.leaves(moved)) > 0.0


def test_bad_moment_dtype_rejected():
    with pytest.raises(ValueError, match="moment_dtype"):
        dataclasses.replace(
            cfg_lib.oryx_tiny().train, moment_dtype="float16"
        )
