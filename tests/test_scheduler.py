"""Continuous-batching scheduler: slot freeing + admission at chunk
boundaries, FIFO no-starvation, page-pressure eviction with
deterministic replay, and the wasted-step microbench as a slow test."""

import numpy as np
import pytest

import jax

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import oryx
from oryx_tpu.serve.pipeline import OryxInference
from oryx_tpu.serve.scheduler import ContinuousScheduler
from oryx_tpu.utils.metrics import ServingMetrics


class FakeTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


@pytest.fixture(scope="module")
def pipe():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    return OryxInference(FakeTokenizer(), params, cfg)


def _run_all(sched, reqs):
    """Submit before starting (deterministic admission order), then
    collect every reply."""
    handles = [
        sched.submit({"question": q}, cap, sampling)
        for q, cap, sampling in reqs
    ]
    sched.start()
    results = [h.result(timeout=600) for h in handles]
    sched.close()
    return handles, results


def test_short_row_frees_slot_and_admits_within_chunk(pipe):
    """The headline continuous-batching behavior: with 2 slots and 3
    requests, the short row's finish must free its slot and the queued
    request must be admitted at that SAME chunk boundary — and every
    reply must equal the solo pipeline answer (greedy determinism across
    batch composition)."""
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    reqs = [("hello there", 3, None), ("what now?", 12, None),
            ("tell me more", 5, None)]
    handles, results = _run_all(sched, reqs)
    for (q, cap, _), (reply, reason, usage) in zip(reqs, results):
        assert reply == pipe.chat(q, max_new_tokens=cap), q
        assert reason == "length"  # tiny vocab never emits EOS
        assert usage[1] == cap
    # Request 3 waited for a slot, then entered at the chunk boundary
    # where request 1 finished (no full-batch drain in between).
    finish_1 = handles[0].debug["finish_chunk"]
    admit_3 = handles[2].debug["admit_chunk"]
    assert admit_3 <= finish_1, (admit_3, finish_1)
    assert metrics.get("admitted") == 3
    assert metrics.get("completed") == 3
    assert metrics.get("decode_steps_wasted") < metrics.get(
        "decode_steps_total"
    )


def test_no_starvation_fifo(pipe):
    """More requests than slots: everyone completes, and admission
    follows submission order (the FIFO head is never jumped)."""
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        autostart=False,
    )
    reqs = [(f"question number {i}", 4 + (i % 3), None) for i in range(6)]
    handles, results = _run_all(sched, reqs)
    for (q, cap, _), (reply, _, _) in zip(reqs, results):
        assert reply == pipe.chat(q, max_new_tokens=cap), q
    admit_order = [h.debug["admit_chunk"] for h in handles]
    assert admit_order == sorted(admit_order), admit_order


def test_mixed_sampling_configs_share_one_engine(pipe):
    """Greedy and sampled requests decode side by side (per-slot
    sampling state): the greedy rows still match pipe.chat exactly and
    a seeded sampled row is reproducible across runs."""
    reqs = [
        ("hello there", 5, None),
        ("what now?", 5, {"temperature": 0.9, "top_p": 0.9, "seed": 3}),
        ("tell me more", 5, None),
    ]
    replies = []
    for _ in range(2):
        sched = ContinuousScheduler(
            pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
            autostart=False,
        )
        _, results = _run_all(sched, reqs)
        replies.append([r[0] for r in results])
    for i in (0, 2):
        assert replies[0][i] == pipe.chat(reqs[i][0], max_new_tokens=5)
    # Same seed, different batch timing possible -> same sampled reply.
    assert replies[0][1] == replies[1][1]


def test_eviction_requeues_and_replays(pipe):
    """Page pressure: a pool too small for both rows' growth evicts the
    YOUNGER slot, which re-queues, replays deterministically after the
    older finishes, and still returns the exact solo reply."""
    q1, q2 = "hello there", "tell me more"
    # Size the pool so both prompts admit, but the pool cannot hold both
    # rows' grown contexts: each row eventually needs pages_for(L + cap
    # + chunk) pages; give the pool one growth page only.
    chunk, ps = 4, 16
    ids1 = len(pipe._prepare_request({"question": q1})[0])
    ids2 = len(pipe._prepare_request({"question": q2})[0])
    import math

    admit1 = math.ceil((ids1 + chunk) / ps)
    admit2 = math.ceil((ids2 + chunk) / ps)
    cap = (admit1 * ps - ids1) + ps  # forces one extra page per row
    metrics = ServingMetrics()
    # prefix_cache off: the template prefix both prompts share would
    # otherwise be SPLICED (shared pages), dissolving the engineered
    # pressure — this test targets the eviction machinery itself
    # (tests/test_prefix_cache.py covers eviction WITH sharing).
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=chunk, max_ctx=512,
        num_pages=admit1 + admit2 + 1, metrics=metrics, autostart=False,
        prefix_cache=False,
    )
    handles, results = _run_all(
        sched, [(q1, cap, None), (q2, cap, None)]
    )
    assert metrics.get("evicted") >= 1
    for q, (reply, reason, usage) in zip((q1, q2), results):
        assert reply == pipe.chat(q, max_new_tokens=cap), q
        assert usage[1] == cap


def test_request_too_large_errors_cleanly(pipe):
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        autostart=False,
    )
    h = sched.submit({"question": "hi"}, 2048)
    ok = sched.submit({"question": "hello there"}, 4)
    sched.start()
    with pytest.raises(RuntimeError, match="max_ctx"):
        h.result(timeout=600)
    # The oversized request must not wedge the queue behind it.
    reply, _, _ = ok.result(timeout=600)
    assert reply == pipe.chat("hello there", max_new_tokens=4)
    sched.close()


@pytest.mark.slow
def test_bench_wasted_step_fraction_drops_2x():
    """Acceptance gate: on the skewed workload the scheduler's
    wasted-step fraction is >= 2x lower than the window batcher's, and
    occupancy is reported."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_serving_sched",
        os.path.join(
            os.path.dirname(__file__), "..", "scripts",
            "bench_serving_sched.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run([])
    assert out["wasted_frac_ratio"] >= 2.0, out
    assert 0.0 < out["scheduler"]["step_utilization"] <= 1.0


def test_request_traces_cover_lifecycle_and_eviction(pipe):
    """Flight-recorder span trees: every request records queue_wait ->
    admission -> prefill -> decode chunks -> emission; an evicted
    request additionally records the evicted event, a reopened
    queue_wait, and a replay prefill."""
    import math

    from oryx_tpu.utils import trace as trace_lib

    q1, q2 = "hello there", "tell me more"
    chunk, ps = 4, 16
    ids1 = len(pipe._prepare_request({"question": q1})[0])
    ids2 = len(pipe._prepare_request({"question": q2})[0])
    admit1 = math.ceil((ids1 + chunk) / ps)
    admit2 = math.ceil((ids2 + chunk) / ps)
    cap = (admit1 * ps - ids1) + ps
    metrics = ServingMetrics()
    tracer = trace_lib.Tracer()
    # prefix_cache off for the same reason as
    # test_eviction_requeues_and_replays: shared template pages would
    # dissolve the page pressure this test relies on.
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=chunk, max_ctx=512,
        num_pages=admit1 + admit2 + 1, metrics=metrics, autostart=False,
        tracer=tracer, prefix_cache=False,
    )
    handles, results = _run_all(
        sched, [(q1, cap, None), (q2, cap, None)]
    )
    assert metrics.get("evicted") >= 1
    for h, (reply, reason, usage) in zip(handles, results):
        tr = h.trace
        assert tr is tracer.get(h.request_id)
        assert tr.done
        assert tr.meta["finish_reason"] == reason
        assert tr.meta["completion_tokens"] == usage[1]
        names = [s.name for s in tr.spans]
        for want in ("queue_wait", "admission", "prefill",
                     "decode_chunk", "emission"):
            assert want in names, (want, names)
        assert all(s.dur_ns is not None for s in tr.spans)
    # The evicted request (the younger one) carries the eviction story.
    evicted = next(
        h.trace for h in handles
        if any(s.name == "evicted" for s in h.trace.spans)
    )
    names = [s.name for s in evicted.spans]
    assert names.count("queue_wait") >= 2  # submit + requeue
    prefills = [s for s in evicted.spans if s.name == "prefill"]
    assert len(prefills) >= 2
    assert prefills[-1].args["replay"] is True
    ev = next(s for s in evicted.spans if s.name == "evicted")
    assert ev.args["replay_tokens"] > 0


def test_forced_stall_triggers_exactly_one_watchdog_dump(pipe):
    """Acceptance: a test-injected stall (one decode chunk held past
    the deadline) produces exactly ONE watchdog dump, containing the
    thread stacks and the flight-recorder tail with the stuck
    request."""
    import io
    import time as time_lib

    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        autostart=False, stall_timeout=0.25,
    )
    out = io.StringIO()
    sched.watchdog.out = out
    orig = sched._step_chunk
    stalled = []

    def slow_chunk():
        if not stalled:
            stalled.append(1)
            time_lib.sleep(1.2)  # > 4x the deadline, no beat
        return orig()

    sched._step_chunk = slow_chunk
    h = sched.submit({"question": "hello there"}, 6)
    sched.start()
    reply, _, _ = h.result(timeout=600)
    assert reply == pipe.chat("hello there", max_new_tokens=6)
    # Allow the watchdog thread its final tick, then close.
    deadline = time_lib.monotonic() + 5
    while sched.watchdog.dumps == 0 and time_lib.monotonic() < deadline:
        time_lib.sleep(0.02)
    sched.close()
    assert sched.watchdog.dumps == 1, sched.watchdog.dumps
    text = out.getvalue()
    assert "STALL WATCHDOG" in text
    assert h.request_id in text  # recorder tail names the stuck request
    assert "slow_chunk" in text  # the stack shows where it hung


def test_cancel_in_queue_refreshes_queue_depth_gauge(pipe):
    """Regression: a request cancelled BEFORE admission popped the
    queue without refreshing the queue_depth gauge, pinning it one
    high until the next submit (found during the oryxlint
    self-application pass over the scheduler's guarded state)."""
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=1, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    h = sched.submit({"question": "never mind"}, 4)
    assert metrics.get("queue_depth") == 1
    h.cancelled = True
    sched._admit()  # engine loop body; thread never started
    assert metrics.get("queue_depth") == 0
    assert h.reply is None and not h.done.is_set()
    sched.close()


def test_cancel_drain_rearms_queue_depth_slo(pipe):
    """Regression: a backlog that empties via client cancels never fed
    the anomaly monitor, so the queue_depth_slo episode stayed disarmed
    and the NEXT backlog burst fired no event — the drain side must
    observe the depth, same as the engine-failure path."""
    from oryx_tpu.utils.anomaly import AnomalyMonitor, AnomalyThresholds

    monitor = AnomalyMonitor(
        source="serve", thresholds=AnomalyThresholds(queue_depth_slo=1)
    )
    sched = ContinuousScheduler(
        pipe, num_slots=1, page_size=16, chunk=4, max_ctx=512,
        metrics=ServingMetrics(), autostart=False, anomaly=monitor,
    )
    h1 = sched.submit({"question": "a"}, 4)
    h2 = sched.submit({"question": "b"}, 4)  # depth 2 > 1: fires
    assert monitor.counts.get("queue_depth_slo") == 1
    h1.cancelled = True
    h2.cancelled = True
    sched._admit()  # engine loop body; thread never started
    # The cancel drain observed depth 0 <= slo/2: episode re-armed,
    # so a second burst fires a second event.
    hc = sched.submit({"question": "c"}, 4)
    hd = sched.submit({"question": "d"}, 4)
    assert monitor.counts.get("queue_depth_slo") == 2
    hc.cancelled = True
    hd.cancelled = True
    sched._admit()  # drain + re-arm again
    # Same invariant on the admission-rejection pop: a burst of invalid
    # requests (prompt + max_tokens > max_ctx) fires the third event at
    # submit, drains through the except path, and must re-arm for the
    # fourth burst.
    h3 = sched.submit({"question": "e"}, 4096)
    h4 = sched.submit({"question": "f"}, 4096)
    assert monitor.counts.get("queue_depth_slo") == 3
    sched._admit()
    for h in (h3, h4):
        assert h.error_kind == "invalid_request"
    sched.submit({"question": "g"}, 4)
    sched.submit({"question": "h"}, 4)
    assert monitor.counts.get("queue_depth_slo") == 4
    sched.close()


def test_engine_error_drains_queue_and_resets_gauge(pipe, monkeypatch):
    """Regression: the engine-failure handler drained the queue without
    refreshing the queue_depth gauge — /metrics kept reporting the dead
    backlog until the next submit. Same every-pop-refreshes-the-gauge
    invariant as the pre-admission cancel path."""
    from oryx_tpu.serve import scheduler as sched_mod

    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=1, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )

    def boom(*a, **k):
        raise RuntimeError("induced device failure")

    monkeypatch.setattr(sched_mod.generate_lib, "paged_prefill", boom)
    h1 = sched.submit({"question": "first"}, 4)
    h2 = sched.submit({"question": "queued behind"}, 4)
    sched.start()
    for h in (h1, h2):
        with pytest.raises(RuntimeError, match="induced device failure"):
            h.result(timeout=120)
    assert metrics.get("queue_depth") == 0
    sched.close()


def test_request_cost_ledger_complete_and_consistent(pipe):
    """Every finished request carries the full cost ledger (the
    capacity harness's acceptance bar): prefill + cached tokens
    partition the prompt, decode steps cover the decode, page-seconds
    and the span-derived wall times are positive and sane — and the
    look-alike second request shows its shared prefix as CACHED tokens
    (the TokenTrie splice visible in per-request cost)."""
    from oryx_tpu.utils.metrics import REQUEST_COST_KEYS

    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    shared = "shared system preamble for the ledger test " * 2
    reqs = [(shared + "q one?", 4, None), (shared + "q two?", 4, None)]
    handles, results = _run_all(sched, reqs)
    for h, (reply, reason, usage) in zip(handles, results):
        cost = h.debug["cost"]
        assert set(REQUEST_COST_KEYS) <= set(cost), cost
        # Prompt tokens either came from the cache or were computed.
        assert cost["prefill_tokens"] + cost["cached_tokens"] == usage[0]
        assert cost["decode_steps"] >= 4  # at least one decode chunk
        assert cost["page_seconds"] > 0
        assert cost["prefill_s"] > 0
        assert cost["queue_s"] >= 0
        assert cost["decode_s"] > 0
        assert cost["e2e_s"] > 0
        # The ledger also lands in the trace meta (what
        # /debug/requests serves).
        assert h.trace.summary()["meta"]["cost"] == cost
    # First admission is cold; the second splices the shared prefix.
    assert handles[0].debug["cost"]["cached_tokens"] == 0
    assert handles[1].debug["cost"]["cached_tokens"] > 0
    # Aggregate histogram families observed one sample per request.
    text = metrics.render()
    import re

    for fam in ("request_prefill_tokens", "request_cached_tokens",
                "request_decode_steps", "request_page_seconds",
                "request_queue_seconds", "request_prefill_seconds",
                "request_decode_seconds", "request_e2e_seconds"):
        m = re.search(
            rf"^oryx_serving_{fam}_count (\d+)$", text, re.M
        )
        assert m and int(m.group(1)) == 2, fam


def test_cost_ledger_survives_eviction_replay(pipe):
    """An evicted-and-replayed request's ledger keeps accumulating:
    the replay re-pays prefill (prefill + cached tokens exceed one
    placement's prompt) and page-seconds never reset. The ledger
    reports what was SPENT, not what one placement used."""
    q1, q2 = "hello there", "tell me more"
    chunk, ps = 4, 16
    ids1 = len(pipe._prepare_request({"question": q1})[0])
    ids2 = len(pipe._prepare_request({"question": q2})[0])
    import math

    admit1 = math.ceil((ids1 + chunk) / ps)
    admit2 = math.ceil((ids2 + chunk) / ps)
    cap = (admit1 * ps - ids1) + ps
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=chunk, max_ctx=512,
        num_pages=admit1 + admit2 + 1, metrics=metrics, autostart=False,
        prefix_cache=False,
    )
    handles, results = _run_all(
        sched, [(q1, cap, None), (q2, cap, None)]
    )
    assert metrics.get("evicted") >= 1
    total_prefill = sum(
        h.debug["cost"]["prefill_tokens"] + h.debug["cost"]["cached_tokens"]
        for h in handles
    )
    # At least one request prefilled twice (eviction replay).
    assert total_prefill > ids1 + ids2
    for h in handles:
        assert h.debug["cost"]["page_seconds"] > 0


def test_cancelled_in_queue_gets_zero_cost_ledger(pipe):
    """Review fix: a request cancelled while still QUEUED finishes its
    trace as done-without-error, so the /debug/requests?state=done
    audit sees it — it must carry a (zero-resource) cost ledger like
    every other finished request."""
    import time as time_lib

    sched = ContinuousScheduler(
        pipe, num_slots=1, page_size=16, chunk=4, max_ctx=512,
        autostart=False,
    )
    h1 = sched.submit({"question": "hello there"}, 3)
    h2 = sched.submit({"question": "tell me more"}, 3)
    h2.cancelled = True  # client hung up while queued behind h1
    sched.start()
    assert h1.result(timeout=600)[0]
    for _ in range(200):  # the engine pops h2 at a later loop pass
        if h2.trace.done:
            break
        time_lib.sleep(0.05)
    sched.close()
    meta = h2.trace.summary()["meta"]
    assert meta.get("cancelled") is True
    cost = meta["cost"]
    assert cost["prefill_tokens"] == 0
    assert cost["cached_tokens"] == 0
    assert cost["decode_steps"] == 0
    assert cost["page_seconds"] == 0
    assert cost["queue_s"] >= 0 and cost["e2e_s"] >= 0
    assert h2.debug["cost"] == cost


def test_cancelled_in_queue_increments_cancelled_counter(pipe):
    """Regression for the queue-cancel undercount (oryxlint
    terminal-path obligation finding on scheduler.py `_cancel_queued`:
    `cancelled` undischarged): the pre-admission cancel path finalized
    the ledger and emitted the wide event but skipped
    `metrics.inc("cancelled")`, so the counter only saw the three
    slot-holding cancel paths and queue cancels undercounted. All four
    cancel exits now route through `_cancel_queued`/`_cancel_slot`,
    each carrying a machine-checked `# obligations:` set."""
    import time as time_lib

    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=1, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    h1 = sched.submit({"question": "hello there"}, 3)
    h2 = sched.submit({"question": "tell me more"}, 3)
    h2.cancelled = True  # client hung up while queued behind h1
    sched.start()
    assert h1.result(timeout=600)[0]
    for _ in range(200):  # the engine pops h2 at a later loop pass
        if h2.trace.done:
            break
        time_lib.sleep(0.05)
    sched.close()
    assert metrics.get("cancelled") == 1


def test_queued_deadline_rejection_carries_cost_ledger(pipe):
    """Review fix: a request that dies while still QUEUED (deadline
    expired before admission) is a terminal path too — its ledger
    (zero resources, real queue wait) must land in the handle and the
    trace meta, so saturated-regime cost attribution covers the
    requests that never ran."""
    import time as time_lib

    sched = ContinuousScheduler(
        pipe, num_slots=1, page_size=16, chunk=4, max_ctx=512,
        autostart=False,
    )
    h = sched.submit({"question": "hello there"}, 3, timeout_s=0.01)
    time_lib.sleep(0.05)  # expire before the engine ever runs
    sched.start()
    with pytest.raises(RuntimeError):
        h.result(timeout=600)
    sched.close()
    assert h.error_kind == "timeout"
    cost = h.debug["cost"]
    assert cost["prefill_tokens"] == 0
    assert cost["page_seconds"] == 0
    assert cost["queue_s"] >= 0
    assert h.trace.summary()["meta"]["cost"] == cost


def test_page_seconds_accrual_is_refcount_weighted(pipe):
    """Review fix: a page shared by k holders charges each holder 1/k,
    so summed request_page_seconds never exceeds physical residency —
    without this, the better prefix sharing works, the more expensive
    the aggregate HBM currency would look."""
    import time as time_lib

    from oryx_tpu.serve.scheduler import RequestHandle, _Request

    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        autostart=False,
    )
    p_excl, p_shared = sched.allocator.alloc(2)
    sched.allocator.share([p_shared])  # second holder of p_shared
    def mk():
        r = _Request(
            request={}, max_new=1, sampling={},
            handle=RequestHandle(), submit_time=0.0, stops=[],
        )
        r.pages_t = time_lib.monotonic()
        return r

    ra, rb = mk(), mk()
    sched.slots[0], sched.slots[1] = ra, rb
    sched.bt[0, 0], sched.bt[0, 1] = p_excl, p_shared  # 1 + 1/2
    sched.bt[1, 0] = p_shared  # 1/2
    time_lib.sleep(0.1)
    sched._accrue_page_seconds(0)
    sched._accrue_page_seconds(1)
    a, b = ra.cost_page_seconds, rb.cost_page_seconds
    assert a > 0 and b > 0
    # A holds one exclusive page (weight 1) plus half the shared page;
    # B holds the other half: the ratio is 3 regardless of sleep
    # jitter (both accruals cover near-identical intervals).
    assert 2.5 < a / b < 3.5, (a, b)
    # Drop the fabricated holders so close() leaves a clean pool.
    sched.allocator.free([p_excl, p_shared, p_shared])
    sched.bt[:] = sched.allocator.sentinel
    sched.slots = [None, None]
    sched.close()


def test_stop_string_mid_chunk_not_billed_useful(pipe):
    """Bugfix pin: a slot that finishes mid-chunk on a stop STRING
    (detected host-side, so the token loop consumed the whole chunk)
    must re-bill the steps past the stop completion as wasted —
    without this, bench_serving_sched.py's wasted-step fraction
    under-counts exactly when stop strings end rows early, flattering
    whichever engine wastes more."""
    import time as time_lib

    from oryx_tpu.serve.scheduler import RequestHandle, _Request

    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=1, page_size=16, chunk=8, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    h = RequestHandle()
    tr = sched.tracer.start_trace("request")
    h.trace = tr
    h.request_id = tr.id
    req = _Request(
        request={}, max_new=100, sampling={}, handle=h,
        submit_time=time_lib.monotonic(), stops=["c"], trace=tr,
    )
    req.length = 4
    req.activated = True
    sched.slots[0] = req
    sched.lengths[0] = req.length
    # Device chunk decodes "abcde": the stop "c" completes at token 3;
    # tokens 4-5 did nothing for the client.
    useful = sched._advance(0, [ord(ch) for ch in "abcde"])
    assert h.done.is_set() and h.finish_reason == "stop"
    assert h.usage == (4, 3)
    assert useful == 3, f"steps past the stop billed useful: {useful}"

    # EOS consumed AFTER the stop completed: it is billed by the token
    # loop but never appended to `emitted` — the clamp must count it
    # wasted too (consumed-token space, not emitted-token space).
    h2 = RequestHandle()
    tr2 = sched.tracer.start_trace("request")
    h2.trace = tr2
    h2.request_id = tr2.id
    req2 = _Request(
        request={}, max_new=100, sampling={}, handle=h2,
        submit_time=time_lib.monotonic(), stops=["a"], trace=tr2,
    )
    req2.length = 4
    req2.activated = True
    sched.slots[0] = req2
    sched.lengths[0] = req2.length
    eos = sched.cfg.generation.eos_token_id
    useful = sched._advance(0, [ord("a"), ord("b"), eos, ord("d")])
    assert h2.done.is_set() and h2.finish_reason == "stop"
    assert h2.usage == (4, 1)
    assert useful == 1, f"EOS after the stop billed useful: {useful}"
    sched.close()
