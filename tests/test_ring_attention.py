"""Ring attention vs full attention on the 8-device CPU mesh
(SURVEY.md §4 "Distributed": shard_map tests with no TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from oryx_tpu.ops.attention import attention as full_attention
from oryx_tpu.ops.ring_attention import ring_attention


def _mesh():
    devs = np.asarray(jax.devices()).reshape(-1)
    return Mesh(devs.reshape(len(devs), 1), ("sp", "unused"))


def _qkv(key, B, T, Hq, Hk, D):
    ks = jax.random.split(key, 3)
    return (
        jax.random.normal(ks[0], (B, T, Hq, D), jnp.float32),
        jax.random.normal(ks[1], (B, T, Hk, D), jnp.float32),
        jax.random.normal(ks[2], (B, T, Hk, D), jnp.float32),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(causal):
    mesh = _mesh()
    q, k, v = _qkv(jax.random.key(0), 2, 128, 4, 2, 16)
    ref = full_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_ring_with_padding_mask():
    mesh = _mesh()
    B, T = 2, 64
    q, k, v = _qkv(jax.random.key(1), B, T, 4, 4, 16)
    lengths = jnp.asarray([64, 37], jnp.int32)
    kv_mask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.int32)
    ref = full_attention(q, k, v, causal=True, kv_mask=kv_mask)
    got = ring_attention(q, k, v, mesh=mesh, causal=True, kv_mask=kv_mask)
    for b, n in enumerate([64, 37]):
        np.testing.assert_allclose(
            np.asarray(got)[b, :n], np.asarray(ref)[b, :n], atol=2e-5
        )


def test_ring_grad_matches_full():
    """Differentiable through the ring (training-path requirement)."""
    mesh = _mesh()
    q, k, v = _qkv(jax.random.key(2), 1, 64, 2, 2, 8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_full(causal):
    """Flash-inner ring (Pallas kernel per visiting block, interpret mode
    on CPU) vs full attention."""
    mesh = _mesh()
    q, k, v = _qkv(jax.random.key(3), 2, 128, 4, 2, 16)
    ref = full_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh=mesh, causal=causal, impl="flash")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-5
    )


def test_ring_flash_padding_mask():
    mesh = _mesh()
    B, T = 2, 64
    q, k, v = _qkv(jax.random.key(4), B, T, 4, 4, 16)
    lengths = jnp.asarray([64, 37], jnp.int32)
    kv_mask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.int32)
    ref = full_attention(q, k, v, causal=True, kv_mask=kv_mask)
    got = ring_attention(
        q, k, v, mesh=mesh, causal=True, kv_mask=kv_mask, impl="flash"
    )
    for b, n in enumerate([64, 37]):
        np.testing.assert_allclose(
            np.asarray(got)[b, :n], np.asarray(ref)[b, :n], atol=2e-5
        )


def test_ring_flash_grad_matches_full():
    """The custom-VJP ring backward (dk/dv travel with their blocks) must
    match dense-attention gradients."""
    mesh = _mesh()
    q, k, v = _qkv(jax.random.key(5), 1, 64, 2, 2, 8)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, mesh=mesh, causal=True, impl="flash"
            ) ** 2
        )

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
