"""Query-chunked XLA attention (ops/attention.py): the memory-bounded
`lax.map` path must equal the dense path bit-for-bit per chunk math, so the
biggest packed-video buckets (VERDICT weak #9: 65536-bucket fallback) stay
serviceable without O(P^2) logits."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu.ops import attention as attn_lib


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), jnp.float32
    )


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_matches_dense(monkeypatch, causal):
    B, Tq, Tk, Hq, Hk, D = 2, 32, 32, 4, 2, 8
    q = _rand((B, Tq, Hq, D), 0)
    k = _rand((B, Tk, Hk, D), 1)
    v = _rand((B, Tk, Hk, D), 2)
    seg_q = jnp.asarray(
        np.repeat(np.arange(1, 5), Tq // 4)[None].repeat(B, 0), jnp.int32
    )
    kw = dict(causal=causal, q_segment_ids=seg_q, kv_segment_ids=seg_q)
    dense = attn_lib.attention(q, k, v, **kw)
    # Force chunking: cap → chunk of 8 queries (4 chunks).
    monkeypatch.setattr(attn_lib, "MAX_LOGITS_ELEMS", B * Hq * Tk * 8)
    chunked = attn_lib.attention(q, k, v, **kw)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(chunked), rtol=1e-6, atol=1e-6
    )


def test_chunked_kv_mask_and_decode_shape(monkeypatch):
    B, Tq, Tk, H, D = 1, 16, 16, 2, 4
    q = _rand((B, Tq, H, D), 3)
    k = _rand((B, Tk, H, D), 4)
    v = _rand((B, Tk, H, D), 5)
    kv_mask = jnp.asarray((np.arange(Tk) < 10)[None].repeat(B, 0), jnp.int32)
    dense = attn_lib.attention(q, k, v, causal=True, kv_mask=kv_mask)
    monkeypatch.setattr(attn_lib, "MAX_LOGITS_ELEMS", B * H * Tk * 4)
    chunked = attn_lib.attention(q, k, v, causal=True, kv_mask=kv_mask)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(chunked), rtol=1e-6, atol=1e-6
    )
    # Decode shape (Tq=1) never chunks below one query.
    out = attn_lib.attention(q[:, :1], k, v, kv_mask=kv_mask)
    assert out.shape == (B, 1, H, D)
