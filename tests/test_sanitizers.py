"""Runtime sanitizers: recompile_watchdog catches an induced recompile
loop (and exports oryx_recompiles_total); donation_guard proves
donation and trips on use-after-donate."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oryx_tpu.analysis.sanitizers import (
    RecompileStormError,
    UseAfterDonateError,
    backend_donates,
    donation_guard,
    recompile_watchdog,
)
from oryx_tpu.utils.metrics import Registry


def test_watchdog_catches_induced_recompile_loop():
    """The acceptance scenario: a shape-unstable loop recompiles one
    function per iteration; the watchdog raises and the recompile
    counter lands in the registry as oryx_recompiles_total{fn=...}."""
    reg = Registry(prefix="oryx_serving")

    def storm_fn(x):
        return x * 2 + 1

    f = jax.jit(storm_fn)
    with pytest.raises(RecompileStormError, match="recompile storm"):
        with recompile_watchdog(budget=2, registry=reg) as stats:
            for n in range(1, 6):  # 5 distinct shapes = 5 compiles
                f(jnp.zeros((n,))).block_until_ready()
    assert stats.counts["storm_fn"] == 5
    assert stats.over_budget()["storm_fn"] == 5
    # Compiles beyond the first are recompiles: 4 increments.
    fam = reg.existing("oryx_recompiles_total", raw_name=True)
    assert fam is not None
    assert fam.labels(fn="storm_fn").value == 4.0
    rendered = reg.render()
    assert 'oryx_recompiles_total{fn="storm_fn"} 4' in rendered


def test_watchdog_quiet_within_budget():
    def steady_fn(x):
        return x + 1

    f = jax.jit(steady_fn)
    with recompile_watchdog(budget=1) as stats:
        for _ in range(4):  # one shape: one compile, three cache hits
            f(jnp.zeros((3,))).block_until_ready()
    assert stats.counts.get("steady_fn", 0) <= 1
    assert not stats.over_budget().get("steady_fn")


def test_watchdog_record_mode_does_not_raise():
    def quiet_storm_fn(x):
        return x - 1

    f = jax.jit(quiet_storm_fn)
    with recompile_watchdog(budget=1, action="record") as stats:
        for n in range(7, 10):
            f(jnp.zeros((n,))).block_until_ready()
    assert stats.counts["quiet_storm_fn"] == 3
    assert stats.over_budget()["quiet_storm_fn"] == 3


def test_watchdog_restores_jax_logging_config():
    before = jax.config.jax_log_compiles
    with recompile_watchdog(budget=100):
        assert jax.config.jax_log_compiles is True
    assert jax.config.jax_log_compiles == before


def test_watchdog_rejects_bad_action():
    with pytest.raises(ValueError, match="action"):
        with recompile_watchdog(action="explode"):
            pass


def test_donation_guard_proves_consumption_and_trips_on_read():
    if not backend_donates():
        pytest.skip("backend ignores donation; nothing to guard")
    eat = jax.jit(
        lambda kv: {"k": kv["k"] + 1, "v": kv["v"] * 2},
        donate_argnums=0,
    )
    kv = {"k": jnp.ones((8,)), "v": jnp.zeros((8,))}
    with donation_guard(kv, expect_consumed=True, label="kv") as guard:
        out = eat(kv)
        jax.block_until_ready(out)
    assert guard.consumed
    with pytest.raises(UseAfterDonateError, match="use-after-donate"):
        guard.check()
    guard.check(out)  # the fresh tree is fine


def test_donation_guard_flags_unconsumed():
    keep = jax.jit(lambda kv: {"k": kv["k"] + 1})  # no donation
    kv = {"k": jnp.ones((4,))}
    with pytest.raises(AssertionError, match="NOT"):
        with donation_guard(kv, expect_consumed=True):
            jax.block_until_ready(keep(kv))


def test_donation_guard_empty_tracking_is_not_vacuous():
    """Regression: a tree whose leaves are host arrays (a refactor
    hazard) tracked zero device buffers and assert_consumed passed
    while verifying nothing."""
    host_tree = {"k": np.ones((4,))}
    with pytest.raises(AssertionError, match="no jax-array leaves"):
        with donation_guard(host_tree, expect_consumed=True):
            pass
