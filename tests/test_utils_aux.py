"""Aux-subsystem tests: profiling timer/annotations, metric logger, launch
config files, train-CLI arg surface (SURVEY.md §5)."""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_step_timer_rolls():
    from oryx_tpu.utils.profiling import StepTimer

    t = StepTimer(window=3, n_chips=2)
    assert t.tick(100) is None  # first tick arms
    for _ in range(4):
        stats = t.tick(100)
    assert stats is not None
    assert stats["tokens_per_sec"] > 0
    assert stats["tokens_per_sec_per_chip"] == pytest.approx(
        stats["tokens_per_sec"] / 2
    )
    assert len(t._times) == 3  # window bound


def test_annotate_and_trace_smoke(tmp_path):
    import jax.numpy as jnp

    from oryx_tpu.utils import profiling

    with profiling.annotate("unit-test-region"):
        x = jnp.ones((4,)) + 1
    assert float(x.sum()) == 8.0


def test_metric_logger_writes_jsonl(tmp_path):
    from oryx_tpu.utils.metrics import MetricLogger

    path = str(tmp_path / "m.jsonl")
    lg = MetricLogger(path, log_every=2)
    lg.log_step(1, {"loss": 1.0, "num_tokens": 10})
    lg.log_step(2, {"loss": 0.5, "num_tokens": 10})
    lg.close()
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 1 and lines[0]["step"] == 2
    assert "tokens_per_sec_per_chip" in lines[0]


def test_metric_logger_tensorboard(tmp_path):
    from oryx_tpu.utils.metrics import MetricLogger

    tb_dir = str(tmp_path / "tb")
    lg = MetricLogger(None, log_every=1, tensorboard_dir=tb_dir)
    if lg._tb is None:
        pytest.skip("tensorboard writer unavailable")
    lg.log_step(1, {"loss": 1.0, "num_tokens": 10})
    lg.close()
    assert any(
        f.startswith("events.out.tfevents") for f in os.listdir(tb_dir)
    )


@pytest.mark.parametrize("name", [
    "oryx_7b_sft", "oryx_34b_sft", "oryx_7b_longvideo", "oryx_7b_pretrain",
    "oryx_1_5_32b_sft", "oryx_7b_sft_lora", "oryx_34b_longvideo",
])
def test_launch_configs_load(name):
    from oryx_tpu.config import OryxConfig

    with open(os.path.join(REPO, "scripts", "configs", f"{name}.json")) as f:
        cfg = OryxConfig.from_json(f.read())
    assert cfg.mesh.num_devices >= 4
    # Sequence-parallel meshes train under ring attention ("ring" = xla
    # inner loop, "ring_flash" = Pallas inner — the 32B/34B pod recipe,
    # TPU_VALIDATION round 5); dense meshes use the Pallas kernel.
    if cfg.mesh.sp > 1:
        assert cfg.attn_impl.startswith("ring")
    else:
        assert cfg.attn_impl == "pallas"


def test_train_cli_argparser():
    from oryx_tpu.train.cli import build_argparser

    ap = build_argparser()
    args = ap.parse_args([
        "--config", "c.json", "--data", "d.json",
        "--tokenizer-path", "tok", "--num-steps", "5",
    ])
    assert args.sharding == "fsdp" and args.num_steps == 5


def test_train_cli_end_to_end(tmp_path, monkeypatch):
    """The SFT entry point runs a step on real (tiny, synthetic) data
    and exports a LOADABLE weights-only model dir — the exported tree
    must not drag the optimizer moments along (2/3 of a TrainState)."""
    import json

    import numpy as np
    from PIL import Image

    import dataclasses

    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.serve import builder
    from oryx_tpu.train import cli as train_cli

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")

    class FakeTok:
        def encode(self, text, add_special_tokens=False):
            return [min(ord(c), 500) for c in text]

        def decode(self, ids, skip_special_tokens=True):
            return "".join(chr(i) for i in ids if 0 < i < 500)

    import transformers

    monkeypatch.setattr(
        transformers.AutoTokenizer, "from_pretrained",
        staticmethod(lambda *a, **k: FakeTok()),
    )

    cfg = cfg_lib.oryx_tiny()
    cfg = dataclasses.replace(
        cfg,
        mesh=cfg_lib.MeshConfig(dp=2, fsdp=4),
        train=dataclasses.replace(
            cfg.train, global_batch_size=8, num_train_steps=1,
            checkpoint_dir=str(tmp_path / "ckpt"), log_every=1,
        ),
    )
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(cfg.to_json())

    img = tmp_path / "img.png"
    Image.fromarray(
        np.random.default_rng(0).integers(0, 255, (28, 28, 3), dtype=np.uint8)
    ).save(img)
    records = [
        {"id": i, "image": img.name, "conversations": [
            {"from": "human", "value": "<image>\nwhat?"},
            {"from": "gpt", "value": "thing"},
        ]}
        for i in range(8)
    ]
    data_path = tmp_path / "data.json"
    data_path.write_text(json.dumps(records))
    out_dir = tmp_path / "model"

    train_cli.main([
        "--config", str(cfg_path), "--data", str(data_path),
        "--media-root", str(tmp_path), "--tokenizer-path", "unused",
        "--output-dir", str(out_dir), "--num-steps", "1",
    ])

    _, params, cfg2 = builder.load_pretrained_model(
        str(out_dir), tokenizer=FakeTok()
    )
    assert cfg2.llm == cfg.llm
    # Weights-only export: model subtrees, no TrainState wrapper.
    assert set(params) == {"llm", "vit", "compressor"}
