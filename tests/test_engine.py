"""The Engine interface (serve/engine.py): protocol conformance of the
reference implementation, factory-registry error behavior, and the
sharded engine's mesh invariants."""

import pytest

import jax

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import oryx
from oryx_tpu.serve.engine import (
    Engine,
    create_engine,
    engine_names,
    register_engine,
)
from oryx_tpu.serve.pipeline import OryxInference
from oryx_tpu.serve.scheduler import ContinuousScheduler


class FakeTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


@pytest.fixture(scope="module")
def pipe():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    return OryxInference(FakeTokenizer(), params, cfg)


def test_registry_names():
    assert "continuous" in engine_names()
    assert "sharded" in engine_names()


def test_continuous_scheduler_satisfies_engine_protocol(pipe):
    """The reference implementation must carry EVERY protocol member —
    a scheduler refactor that sheds one (readiness, cancel, drain,
    fail_inflight...) breaks the router/supervisor/API-server contract
    and must fail here, not in production."""
    sched = create_engine(
        "continuous", pipe, num_slots=2, page_size=16, chunk=4,
        max_ctx=512, autostart=False,
    )
    assert isinstance(sched, ContinuousScheduler)
    assert isinstance(sched, Engine)
    # readiness() before start: the loop thread is not alive.
    ready, reason = sched.readiness()
    assert ready is False and "dead" in reason
    sched.start()
    try:
        assert sched.alive()
        assert sched.readiness() == (True, "ok")
        assert sched.queue_len() == 0
        h = sched.submit({"question": "hello there"}, 3)
        reply, why, usage = h.result(timeout=600)
        assert reply and why in ("stop", "length")
        # cancel() on a finished handle is a no-op flag flip.
        sched.cancel(h)
        assert h.cancelled
    finally:
        sched.stop()
    assert not sched.alive()
    assert sched.stopping


def test_unknown_engine_name_fails_fast(pipe):
    with pytest.raises(ValueError, match="unknown engine"):
        create_engine("warp-drive", pipe)


def test_duplicate_registration_rejected():
    with pytest.raises(ValueError, match="already registered"):
        register_engine("continuous")(lambda pipe, **kw: None)


def test_sharded_engine_requires_tp_mesh(pipe):
    """--engine sharded must never silently fall back to one chip:
    no mesh, a tp-less mesh, and indivisible KV heads all refuse at
    construction."""
    with pytest.raises(ValueError, match="mesh absent"):
        create_engine("sharded", pipe, autostart=False)
    if jax.device_count() >= 2:
        from oryx_tpu.config import MeshConfig
        from oryx_tpu.parallel.mesh import build_mesh

        cfg = pipe.cfg
        fsdp_mesh = build_mesh(
            MeshConfig(fsdp=2), devices=jax.devices()[:2]
        )
        meshed = OryxInference(
            FakeTokenizer(), pipe.params, cfg, mesh=fsdp_mesh,
            sharding_mode="fsdp",
        )
        with pytest.raises(ValueError, match="tp axis"):
            create_engine("sharded", meshed, autostart=False)
    if jax.device_count() >= 4:
        from oryx_tpu.config import MeshConfig
        from oryx_tpu.parallel.mesh import build_mesh

        # tiny cfg has 2 KV heads; tp=4 cannot divide them.
        mesh4 = build_mesh(MeshConfig(tp=4), devices=jax.devices()[:4])
        meshed4 = OryxInference(
            FakeTokenizer(), pipe.params, pipe.cfg, mesh=mesh4,
        )
        with pytest.raises(ValueError, match="do not divide"):
            create_engine("sharded", meshed4, autostart=False)


def test_sharded_engine_builds_on_tp_mesh(pipe):
    if jax.device_count() < 2:
        pytest.skip("needs multiple (CPU) devices")
    from oryx_tpu.config import MeshConfig
    from oryx_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(MeshConfig(tp=2), devices=jax.devices()[:2])
    meshed = OryxInference(FakeTokenizer(), pipe.params, pipe.cfg,
                           mesh=mesh, sharding_mode="tp")
    eng = create_engine(
        "sharded", meshed, num_slots=2, page_size=16, chunk=4,
        max_ctx=512, autostart=False,
    )
    try:
        assert isinstance(eng, Engine)
        assert not eng.kv_pages["k"].sharding.is_fully_replicated
    finally:
        eng.close()
