"""Worker process for tests/test_multiprocess.py — NOT a pytest module.

Each of two processes owns 4 CPU devices (8 global), rendezvouses via
oryx_tpu.parallel.mesh.initialize_distributed (Gloo), builds the SAME
Trainer (dp=2 x fsdp=4 over the global device set), and runs two real
train steps on the same host batch (single-controller semantics: every
process presents the identical host value; GSPMD shards it). Prints one
MP_RESULT JSON line the parent asserts on.

Run directly (in 2 processes):
    python tests/mp_trainer_worker.py <pid> <port> <tmpdir>
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
))
from mp_common import bootstrap  # noqa: E402

pid, jax = bootstrap()

import numpy as np  # noqa: E402

from oryx_tpu import config as cfg_lib  # noqa: E402

from test_trainer_modes import _batch  # noqa: E402

from oryx_tpu.train.trainer import Trainer  # noqa: E402

cfg = dataclasses.replace(
    cfg_lib.oryx_tiny(),
    mesh=cfg_lib.MeshConfig(dp=2, fsdp=4, tp=1, sp=1),
)
cfg = dataclasses.replace(
    cfg,
    train=dataclasses.replace(
        cfg.train, num_train_steps=2, log_every=100, checkpoint_every=2,
        checkpoint_dir=os.path.join(sys.argv[3], "ckpt"),
    ),
)

trainer = Trainer(cfg, sharding_mode="fsdp")
batch = _batch(cfg)
state = trainer.fit(iter([batch, batch]), num_steps=2, resume=False,
                    prefetch=0)
step = int(jax.device_get(state.step))

# Multi-process checkpoint/resume (failure posture A3 at "pod" scale):
# step 2 was saved by BOTH processes through orbax's coordinated save; a
# fresh Trainer must restore it and agree on the resumed step.
trainer2 = Trainer(cfg, sharding_mode="fsdp")
resumed = trainer2.resume_if_available()
assert resumed == 2, resumed
for a, b in zip(
    jax.tree_util.tree_leaves(state.params),
    jax.tree_util.tree_leaves(trainer2.state.params),
):
    assert a.sharding == b.sharding
    for sa, sb in zip(a.addressable_shards, b.addressable_shards):
        assert sa.index == sb.index
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(sa.data)),
            np.asarray(jax.device_get(sb.data)),
        )

# Loss of the final params, recomputed identically on every process — the
# cross-process agreement assertion (GSPMD must give one global answer).
from oryx_tpu.train import step as step_lib  # noqa: E402

mb = {k: jax.numpy.asarray(v) for k, v in batch.items()}
loss, _ = jax.jit(step_lib.microbatch_loss, static_argnames=("cfg",))(
    state.params, cfg, mb
)
print(json.dumps({
    "mp_result": True, "pid": pid, "step": step, "resumed": resumed,
    "process_count": jax.process_count(),
    "loss": round(float(jax.device_get(loss)), 6),
}), flush=True)
