"""Data-pipeline tests: template-aware preprocessing, microbatch collation,
modality-grouped iteration (SURVEY.md §2 "Training entry" / "Trainer
subclass")."""

import numpy as np
import pytest

from oryx_tpu.constants import IGNORE_INDEX, IMAGE_TOKEN_INDEX
from oryx_tpu.conversation import conv_templates
from oryx_tpu.train import data as data_lib


class FakeTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [ord(c) for c in text]


def _decode(ids):
    return "".join(chr(i) for i in ids if i >= 0)


REC = {
    "id": "r0",
    "conversations": [
        {"from": "human", "value": "<image>\nQ?"},
        {"from": "gpt", "value": "A!"},
    ],
    "image": "x.png",
}


def test_preprocess_chatml_matches_get_prompt():
    conv = conv_templates["qwen"].copy()
    ids, labels = data_lib.preprocess_conversation(REC, FakeTokenizer(), conv)
    ref = conv.copy()
    ref.append_message("user", "<image>\nQ?")
    ref.append_message("assistant", "A!")
    # Token stream (sentinels removed) spells exactly the template prompt.
    assert _decode(ids) == ref.get_prompt().replace("<image>", "")
    assert int(np.sum(ids == IMAGE_TOKEN_INDEX)) == 1
    # Supervised region is exactly the assistant reply + separator.
    sup = [i for i, l in zip(ids, labels) if l != IGNORE_INDEX]
    assert _decode(sup) == "A!" + conv.sep


def test_preprocess_vicuna_style():
    conv = conv_templates["v1"].copy()
    ids, labels = data_lib.preprocess_conversation(REC, FakeTokenizer(), conv)
    ref = conv.copy()
    ref.append_message("USER", "<image>\nQ?")
    ref.append_message("ASSISTANT", "A!")
    assert _decode(ids) == ref.get_prompt().replace("<image>", "")
    sup = [i for i, l in zip(ids, labels) if l != IGNORE_INDEX]
    assert _decode(sup) == "A!" + (conv.sep2 or conv.sep)


def test_preprocess_plain_style():
    conv = conv_templates["plain"].copy()
    ids, labels = data_lib.preprocess_conversation(REC, FakeTokenizer(), conv)
    # Plain = bare concatenation, no ChatML markers.
    assert "<|im_start|>" not in _decode(ids)
    assert _decode(ids) == "\nQ?\nA!\n"
    sup = [i for i, l in zip(ids, labels) if l != IGNORE_INDEX]
    assert _decode(sup) == "A!\n"


def _mk_example(seed, n_images=1, modality="image", hw=(28, 28)):
    rng = np.random.default_rng(seed)
    images = [rng.standard_normal((*hw, 3)).astype(np.float32)
              for _ in range(n_images)]
    ids = np.array(
        [65, 66] + [IMAGE_TOKEN_INDEX] * n_images + [67, 68], np.int64
    )
    labels = np.full(ids.shape, IGNORE_INDEX, np.int64)
    labels[-2:] = ids[-2:]
    return data_lib.Example(ids, labels, images, modality)


def test_collate_microbatches_independent_buffers():
    """Each microbatch references ITS OWN packed visual buffer."""
    exs = [_mk_example(i, hw=(28 * (1 + i % 2), 28)) for i in range(4)]
    out = data_lib.collate_microbatches(
        exs, 2, buckets=(16, 64, 256), base_grid=8
    )
    single0 = data_lib.collate(exs[:2], buckets=(16, 64, 256), base_grid=8)
    single1 = data_lib.collate(exs[2:], buckets=(16, 64, 256), base_grid=8)
    for k in out:
        assert out[k].shape[0] == 2, k
        got0 = out[k][0]
        np.testing.assert_array_equal(
            got0[tuple(slice(0, s) for s in single0[k].shape)], single0[k]
        )
        got1 = out[k][1]
        np.testing.assert_array_equal(
            got1[tuple(slice(0, s) for s in single1[k].shape)], single1[k]
        )
    # visual_idx never exceeds each micro's own query buffer.
    q = out["q_region_ids"].shape[1]
    assert out["visual_idx"].max() < q


def test_collate_text_only_batch():
    """Text-only records (no media) collate to an all-padding visual
    buffer; the token stream and labels are intact."""
    ids = np.array([65, 66, 67, 68], np.int64)
    labels = np.full(ids.shape, IGNORE_INDEX, np.int64)
    labels[-2:] = ids[-2:]
    exs = [data_lib.Example(ids, labels, [], "image") for _ in range(2)]
    out = data_lib.collate(exs, buckets=(16, 64, 256), base_grid=8)
    assert not out["is_visual"].any()
    assert out["segment_ids"].shape == (16,)
    assert np.all(out["segment_ids"] == 0)
    np.testing.assert_array_equal(out["token_ids"][0, :4], ids)


def test_collate_microbatches_indivisible_raises():
    exs = [_mk_example(i) for i in range(3)]
    with pytest.raises(ValueError):
        data_lib.collate_microbatches(exs, 2, buckets=(64, 256), base_grid=8)


class _StubDataset:
    """Bypasses tokenizer/media: fixed Examples keyed by modality."""

    def __init__(self, modalities):
        self.records = [
            {"id": i, "image": "x.png" if m == "image" else None,
             "video": "v.mp4" if m == "video" else None}
            for i, m in enumerate(modalities)
        ]
        self._mods = modalities

    def __len__(self):
        return len(self.records)

    def __getitem__(self, i):
        return _mk_example(i, modality=self._mods[i])


def test_grouped_iterator_modality_and_leftover_carry():
    """Small modality groups are not starved: tails carry across epochs."""
    mods = ["image"] * 5 + ["video"] * 3
    ds = _StubDataset(mods)
    it = data_lib.grouped_batch_iterator(
        ds, 2, seed=0, num_epochs=2, buckets=(64, 256), base_grid=8
    )
    batches = list(it)
    # 2 epochs x 8 samples = 16 sample slots; leftovers (1 image + 1 video
    # per epoch) carry: epoch2 sees 5+1 images, 3+1 videos -> 3+2 batches.
    assert len(batches) == 2 + 1 + 3 + 2


def test_grouped_iterator_length_grouping():
    """Within a modality, megabatches sort by length_estimate so batches
    hold similar-length samples; every index still appears exactly once."""

    class _Recording(_StubDataset):
        def __init__(self, mods):
            super().__init__(mods)
            self.seen = []

        def __getitem__(self, i):
            self.seen.append(i)
            return super().__getitem__(i)

    ds = _Recording(["image"] * 8)
    # Distinct text lengths 1..8 words (visual allowance is constant).
    for i, rec in enumerate(ds.records):
        rec["conversations"] = [{"from": "human", "value": "w " * (i + 1)}]
    for _ in data_lib.grouped_batch_iterator(
        ds, 2, seed=0, num_epochs=1, length_group_size=4,  # one megabatch
        buckets=(64, 256), base_grid=8,
    ):
        pass
    assert sorted(ds.seen) == list(range(8))
    # One epoch = one megabatch = globally sorted desc by length: each
    # 2-sample batch is a contiguous descending (idx i has length i+1).
    batches = [ds.seen[j : j + 2] for j in range(0, 8, 2)]
    for b in batches:
        assert b[0] == b[1] + 1


def test_grouped_iterator_accum_layout():
    ds = _StubDataset(["image"] * 8)
    it = data_lib.grouped_batch_iterator(
        ds, 4, seed=0, num_epochs=1, grad_accum_steps=2,
        buckets=(64, 256), base_grid=8,
    )
    b = next(it)
    for k, v in b.items():
        assert v.shape[0] == 2, (k, v.shape)
    assert b["token_ids"].shape[1] == 2  # 4 samples / 2 microbatches


def test_projector_checkpoint_roundtrip(tmp_path):
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.utils import checkpoint as ckpt_lib

    cfg = cfg_lib.oryx_tiny()
    p1 = oryx.init_params(cfg, jax.random.key(0))
    p2 = oryx.init_params(cfg, jax.random.key(1))
    path = str(tmp_path / "projector")  # no .npz suffix on purpose
    ckpt_lib.save_projector_only(path, p1)
    merged = ckpt_lib.load_projector_only(path, p2)
    np.testing.assert_array_equal(
        np.asarray(merged["compressor"]["q_proj"]["kernel"]),
        np.asarray(p1["compressor"]["q_proj"]["kernel"]),
    )
    np.testing.assert_array_equal(
        np.asarray(merged["llm"]["embed"]["weight"]),
        np.asarray(p2["llm"]["embed"]["weight"]),
    )


def _ords(s):
    return [ord(c) for c in s]


def test_golden_v1_ids_and_label_mask():
    """Byte-exact golden for SeparatorStyle.TWO: every token id and every
    label position pinned (SURVEY.md §4 'Golden-file')."""
    from oryx_tpu.conversation import Conversation, SeparatorStyle

    conv = Conversation(
        system="S", roles=("USER", "ASSISTANT"), messages=[],
        sep_style=SeparatorStyle.TWO, sep=" ", sep2="</s>", version="v1",
    )
    rec = {"conversations": [
        {"from": "human", "value": "<image>\nQ?"},
        {"from": "gpt", "value": "A!"},
    ]}
    ids, labels = data_lib.preprocess_conversation(rec, FakeTokenizer(), conv)
    expected_ids = (
        _ords("S ")                       # system + sep
        + _ords("USER: ")                 # role prefix (trailing space!)
        + [IMAGE_TOKEN_INDEX]             # <image> sentinel
        + _ords("\nQ? ")                  # user text + sep
        + _ords("ASSISTANT: ")            # open role prefix
        + _ords("A!</s>")                 # supervised reply + sep2
    )
    assert list(ids) == expected_ids
    n_sup = len("A!</s>")
    expected_labels = [IGNORE_INDEX] * (len(expected_ids) - n_sup) + _ords(
        "A!</s>"
    )
    assert list(labels) == expected_labels


def test_golden_chatml_ids_and_label_mask():
    from oryx_tpu.conversation import Conversation, SeparatorStyle

    conv = Conversation(
        system="S", roles=("user", "assistant"), messages=[],
        sep_style=SeparatorStyle.CHATML, sep="<|im_end|>\n", version="qwen",
    )
    rec = {"conversations": [
        {"from": "human", "value": "Q"},
        {"from": "gpt", "value": "A"},
    ]}
    ids, labels = data_lib.preprocess_conversation(rec, FakeTokenizer(), conv)
    expected_ids = (
        _ords("<|im_start|>system\nS<|im_end|>\n")
        + _ords("<|im_start|>user\n")
        + _ords("Q<|im_end|>\n")
        + _ords("<|im_start|>assistant\n")
        + _ords("A<|im_end|>\n")
    )
    assert list(ids) == expected_ids
    n_sup = len("A<|im_end|>\n")
    assert list(labels) == [IGNORE_INDEX] * (
        len(expected_ids) - n_sup
    ) + _ords("A<|im_end|>\n")


def test_golden_prompt_prefix_agreement_all_templates():
    """For every registered template: the unsupervised prefix of the
    training tokenization equals the tokenized generation prompt — the
    train/infer agreement that the v1 trailing-space bug broke."""
    from oryx_tpu.data import mm_utils

    for name, conv in conv_templates.items():
        rec = {"conversations": [
            {"from": "human", "value": "Q?"},
            {"from": "gpt", "value": "A!"},
        ]}
        ids, labels = data_lib.preprocess_conversation(
            rec, FakeTokenizer(), conv
        )
        prefix = [
            int(i) for i, l in zip(ids, labels) if l == IGNORE_INDEX
        ]
        gen = conv.copy()
        gen.append_message(gen.roles[0], "Q?")
        gen.append_message(gen.roles[1], None)
        prompt_ids = [
            int(t) for t in
            mm_utils.tokenizer_image_token(gen.get_prompt(), FakeTokenizer())
        ]
        assert prefix == prompt_ids, f"template {name!r} train/infer mismatch"


def test_collate_frame_separator_ids():
    """The collator's video-placeholder expansion honors
    frame_separator_ids (parity hook): separator TEXT tokens follow each
    frame's visual span, label-masked IGNORE_INDEX; default off keeps
    the contiguous layout byte-identical."""
    rng = np.random.default_rng(0)
    frames = [rng.standard_normal((28, 28, 3)).astype(np.float32)
              for _ in range(3)]
    ids = np.array([65, 66, IMAGE_TOKEN_INDEX, 67, 68], np.int64)
    labels = np.full(ids.shape, IGNORE_INDEX, np.int64)
    labels[-2:] = ids[-2:]
    ex = data_lib.Example(ids, labels, frames, "video")

    base = data_lib.collate([ex], buckets=(16, 64, 256), base_grid=8)
    sep = data_lib.collate(
        [ex], buckets=(16, 64, 256), base_grid=8,
        frame_separator_ids=(42,),
    )
    n_base = int(np.sum(base["attn_mask"][0]))
    n_sep = int(np.sum(sep["attn_mask"][0]))
    assert n_sep == n_base + 3  # one separator per frame
    toks = sep["token_ids"][0, :n_sep]
    isv = sep["is_visual"][0, :n_sep]
    # Non-visual slots: prefix text, one 42 after each frame, suffix.
    np.testing.assert_array_equal(
        toks[~isv], [65, 66, 42, 42, 42, 67, 68])
    # Inserted separators are never supervised: labels are shifted left
    # by one (label AT slot t supervises slot t+1), so a separator at
    # slot s would be a predicted target iff lab[s-1] == 42.
    lab = sep["labels"][0]
    sep_slots = np.where(toks == 42)[0]
    assert len(sep_slots) == 3
    for s in sep_slots:
        assert lab[s - 1] == IGNORE_INDEX


def test_preprocess_llama2_style():
    """LLAMA_2/[INST] family (llava_llama_2, mistral_instruct): training
    masking and the inference prompt agree byte-for-byte, and only the
    assistant reply + closing </s> is supervised."""
    for name in ("llava_llama_2", "mistral_instruct"):
        conv = conv_templates[name].copy()
        ids, labels = data_lib.preprocess_conversation(
            REC, FakeTokenizer(), conv
        )
        ref = conv.copy()
        ref.append_message(conv.roles[0], "<image>\nQ?")
        ref.append_message(conv.roles[1], "A!")
        assert _decode(ids) == ref.get_prompt().replace("<image>", ""), name
        sup = [i for i, l in zip(ids, labels) if l != IGNORE_INDEX]
        assert _decode(sup) == " A! " + conv.sep2, name
        # The system prompt (when present) is inside the first [INST]
        # block and never supervised.
        if conv.system:
            assert "<<SYS>>" in _decode(ids), name
