"""Debug/sanitizer utility tests (SURVEY.md §5 "Race detection /
sanitizers" — the rebuild's numeric-debug posture)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu.utils import debug


def test_debug_mode_restores_flags():
    before = jax.config.jax_debug_nans
    with debug.debug_mode(nan_checks=True):
        assert jax.config.jax_debug_nans
    assert jax.config.jax_debug_nans == before


def test_nan_check_faults_inside_jit():
    with debug.debug_mode(nan_checks=True):
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)).block_until_ready()


def test_assert_finite_tree():
    ok = {"a": jnp.ones((2,)), "b": {"c": jnp.zeros((3,))}}
    debug.assert_finite_tree(ok)
    bad = {"a": jnp.ones((2,)), "b": {"c": jnp.asarray([1.0, np.nan])}}
    with pytest.raises(FloatingPointError, match="b.*c"):
        debug.assert_finite_tree(bad, "grads")
    ints = {"ids": jnp.arange(3)}
    debug.assert_finite_tree(ints)  # non-float leaves are skipped
