"""Pallas kernel lowering checks against the REAL XLA:TPU compiler.

The local libtpu supports chipless topology AOT compiles (TPU_VALIDATION
round 5), and Pallas kernels lower in them — so the suite can catch TPU
lowering regressions (bad block shapes, dtype issues, grid math that
only the Mosaic compiler rejects) without the flaky tunnel. These
compile the SAME kernel variants `scripts/tpu_validate.py` runs
numerically on-chip:

  * causal GQA prefill, fwd and fwd+bwd (custom-VJP path, remat tags)
  * segment-packed varlen (the ViT packing case)
  * KV-cache decode (arbitrary q positions, kv_mask)

Compile-only: a topology target has no devices to execute on. Numeric
parity stays the job of the on-chip tpu_validate run (r3 table). One
topology compile at a time per box (libtpu lockfile) — pytest is
serial, so this is safe in-suite.
"""

import pytest

import jax
import jax.numpy as jnp


def _v5e_device():
    import importlib.util

    if importlib.util.find_spec("libtpu") is None:
        pytest.skip("libtpu not installed (TPU topology AOT unavailable)")
    from jax.experimental import topologies

    try:
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name="v5e:2x2"
        )
    except Exception as e:
        if "libtpu" in str(e) and "lockfile" in str(e):
            # One topology compile at a time per box: a concurrently
            # running agenda/estimator holds /tmp/libtpu_lockfile.
            pytest.skip(f"libtpu lockfile held concurrently: {e}")
        raise
    return topo.devices[0]


def _sds(shape, dev, dtype=jnp.bfloat16):
    return jax.ShapeDtypeStruct(
        shape, dtype, sharding=jax.sharding.SingleDeviceSharding(dev)
    )


@pytest.mark.slow
def test_flash_causal_fwd_bwd_compiles_for_v5e():
    from oryx_tpu.ops.pallas.flash_attention import flash_attention

    dev = _v5e_device()
    B, T, Hq, Hk, D = 2, 1024, 8, 2, 128

    def fwd(q, k, v):
        return flash_attention(q, k, v, causal=True)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    args = (_sds((B, T, Hq, D), dev), _sds((B, T, Hk, D), dev),
            _sds((B, T, Hk, D), dev))
    c = jax.jit(fwd).lower(*args).compile()
    assert c.memory_analysis().temp_size_in_bytes > 0
    # Custom-VJP backward kernel lowers too.
    jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(*args).compile()


@pytest.mark.slow
def test_flash_segment_varlen_compiles_for_v5e():
    from oryx_tpu.ops.pallas.segment_attention import segment_attention

    dev = _v5e_device()
    B, T, H, D = 1, 768, 4, 64

    def fwd(q, k, v, seg):
        return segment_attention(q, k, v, seg, seg)

    jax.jit(fwd).lower(
        _sds((B, T, H, D), dev), _sds((B, T, H, D), dev),
        _sds((B, T, H, D), dev), _sds((B, T), dev, jnp.int32),
    ).compile()


@pytest.mark.slow
def test_flash_decode_compiles_for_v5e():
    from oryx_tpu.ops.pallas.flash_attention import flash_attention

    dev = _v5e_device()
    B, Tq, S, Hq, Hk, D = 4, 8, 2048, 8, 2, 128

    def decode(q, k, v, q_pos, kv_mask):
        return flash_attention(
            q, k, v, causal=True,
            q_positions=q_pos, kv_positions=None, kv_mask=kv_mask,
        )

    jax.jit(decode).lower(
        _sds((B, Tq, Hq, D), dev), _sds((B, S, Hk, D), dev),
        _sds((B, S, Hk, D), dev), _sds((B, Tq), dev, jnp.int32),
        _sds((B, S), dev, jnp.bool_),
    ).compile()
