"""Trainer failure containment under injected faults: checkpoint-save
retry with a pinned backoff schedule, data-loader skip-and-requeue,
corrupt-batch -> skip_nonfinite, and the headline scenario — a mid-run
crash auto-resumes from the last good checkpoint with a bit-identical
loss trajectory."""

import dataclasses
import json

import numpy as np
import pytest

import jax

from oryx_tpu import config as cfg_lib
from oryx_tpu.constants import IGNORE_INDEX, IMAGE_TOKEN_INDEX
from oryx_tpu.models import splice
from oryx_tpu.ops import packing
from oryx_tpu.train.trainer import Trainer
from oryx_tpu.utils import faults
from oryx_tpu.utils.checkpoint import (
    CheckpointManager,
    save_projector_only,
)
from oryx_tpu.utils.retry import BackoffPolicy


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(tmp_path, name, *, steps=4, ckpt_every=1):
    cfg = cfg_lib.oryx_tiny()
    return dataclasses.replace(
        cfg,
        mesh=cfg_lib.MeshConfig(dp=2, fsdp=4, tp=1, sp=1),
        train=dataclasses.replace(
            cfg.train,
            num_train_steps=steps, log_every=1,
            checkpoint_every=ckpt_every,
            checkpoint_dir=str(tmp_path / name),
        ),
    )


def _batch(cfg, seed):
    """One deterministic multimodal batch; distinct `seed`s make the
    loss trajectory step-dependent (a resume mismatch cannot hide)."""
    rng = np.random.default_rng(seed)
    p = cfg.vision.patch_size
    imgs = [
        rng.standard_normal((2 * p, 2 * p, 3)).astype(np.float32)
        for _ in range(8)
    ]
    packed = packing.pack_images(
        imgs, patch_size=p, base_grid=cfg.vision.base_grid,
        side_factors=1, buckets=(64, 256),
    )
    slots = splice.query_slots(packed)
    ids, labels = [], []
    for _ in range(8):
        row = np.concatenate(
            [[5, IMAGE_TOKEN_INDEX], rng.integers(3, 500, 6)]
        )
        lab = np.full(row.shape, IGNORE_INDEX, np.int64)
        lab[-6:] = row[-6:]
        ids.append(row)
        labels.append(lab)
    mm = splice.build_mm_batch(ids, slots, labels=labels, buckets=(16, 64))
    return {
        "patches": packed.patches, "segment_ids": packed.segment_ids,
        "pos_coords": packed.pos_coords, "region_ids": packed.region_ids,
        "q_region_ids": packed.q_region_ids, "token_ids": mm.token_ids,
        "visual_idx": mm.visual_idx, "is_visual": mm.is_visual,
        "attn_mask": mm.attn_mask, "positions": mm.positions,
        "labels": mm.labels,
    }


def _batches(cfg, n):
    return [_batch(cfg, seed=100 + i) for i in range(n)]


def _losses(metrics_path) -> dict[int, float]:
    out = {}
    for line in metrics_path.read_text().splitlines():
        rec = json.loads(line)
        out[rec["step"]] = rec["loss"]
    return out


# ---------------------------------------------------------------------------
# Checkpoint-save retry (no trainer needed: manager-level)
# ---------------------------------------------------------------------------


def test_checkpoint_save_retries_injected_failures(tmp_path):
    slept = []
    mgr = CheckpointManager(
        str(tmp_path / "ck"),
        save_retry=BackoffPolicy(retries=3, base_s=0.5, factor=2.0,
                                 jitter=0.0),
        sleep=slept.append,
    )
    faults.configure("checkpoint_save:times=2")
    state = {"x": np.arange(8, dtype=np.float32)}
    assert mgr.save(1, state) is True
    mgr.wait()
    assert mgr.save_retries == 2
    assert slept == [0.5, 1.0]  # pinned schedule, no wall clock
    assert mgr.latest_step() == 1
    restored = mgr.restore(None)
    np.testing.assert_array_equal(np.asarray(restored["x"]), state["x"])
    mgr.close()


def test_checkpoint_save_budget_exhaustion_raises(tmp_path):
    slept = []
    mgr = CheckpointManager(
        str(tmp_path / "ck2"),
        save_retry=BackoffPolicy(retries=2, base_s=0.1, jitter=0.0),
        sleep=slept.append,
    )
    faults.configure("checkpoint_save:times=10")  # > budget: permanent
    with pytest.raises(faults.FaultInjected):
        mgr.save(1, {"x": np.zeros(2)})
    assert slept == [0.1, 0.2]  # the full bounded budget was spent
    assert mgr.latest_step() is None
    mgr.close()


def test_projector_save_is_atomic(tmp_path):
    cfg = cfg_lib.oryx_tiny()
    from oryx_tpu.models import oryx

    params = oryx.init_params(cfg, jax.random.key(0))
    path = tmp_path / "proj.npz"
    save_projector_only(str(path), params)
    assert path.exists()
    leftovers = [p for p in tmp_path.iterdir() if ".tmp" in p.name]
    assert not leftovers, leftovers
    data = np.load(path)
    assert len(data.files) > 0


# ---------------------------------------------------------------------------
# Data-loader containment
# ---------------------------------------------------------------------------


def test_data_fault_skips_and_preserves_trajectory(tmp_path):
    """A transient loader failure retries the SAME fetch (nothing was
    consumed), so the run completes with the exact fault-free loss
    trajectory — containment that provably changes nothing."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    cfg = _cfg(tmp_path, "clean", steps=2, ckpt_every=100)
    mpath = tmp_path / "clean.jsonl"
    t = Trainer(cfg, sharding_mode="fsdp", metrics_path=str(mpath))
    t.fit(iter(_batches(cfg, 2)), num_steps=2, resume=False, prefetch=0)
    t.close()
    clean = _losses(mpath)

    cfg2 = _cfg(tmp_path, "faulted", steps=2, ckpt_every=100)
    mpath2 = tmp_path / "faulted.jsonl"
    faults.configure("data_loader_next:after=1")  # 2nd fetch fails once
    t2 = Trainer(cfg2, sharding_mode="fsdp", metrics_path=str(mpath2))
    t2.fit(iter(_batches(cfg2, 2)), num_steps=2, resume=False, prefetch=0)
    t2.close()
    assert t2.data_faults == 1
    assert faults.injected_count("data_loader_next") == 1
    assert _losses(mpath2) == clean  # bit-identical despite the fault


def test_data_fault_budget_exhaustion_aborts(tmp_path):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    cfg = _cfg(tmp_path, "deadloader", steps=2, ckpt_every=100)
    faults.configure("data_loader_next:every=1")  # permanently broken
    t = Trainer(cfg, sharding_mode="fsdp", max_data_faults=3)
    with pytest.raises(RuntimeError, match="consecutive data-loader"):
        t.fit(iter(_batches(cfg, 2)), num_steps=2, resume=False,
              prefetch=0)
    t.close()
    assert t.data_faults == 3


def test_corrupt_batch_hits_skip_guard(tmp_path):
    """corrupt=1 at the loader site NaNs one float leaf; the
    skip_nonfinite guard skips the step instead of training on it."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    cfg = _cfg(tmp_path, "poisoned", steps=1, ckpt_every=100)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(
            cfg.train, skip_nonfinite_steps=True
        ),
    )
    mpath = tmp_path / "poisoned.jsonl"
    faults.configure("data_loader_next:corrupt=1,times=1")
    t = Trainer(cfg, sharding_mode="fsdp", metrics_path=str(mpath))
    t.fit(iter(_batches(cfg, 1)), num_steps=1, resume=False, prefetch=0)
    t.close()
    rec = json.loads(mpath.read_text().splitlines()[-1])
    assert rec["skipped"] == 1


# ---------------------------------------------------------------------------
# The headline: injected mid-run crash -> auto-resume, bit-identical
# ---------------------------------------------------------------------------


@pytest.fixture
def _no_persistent_cache():
    """Disable the persistent compilation cache for this test: the
    jax-0.4.37 deserialized-executable donation quirk (see conftest)
    would otherwise make EVERY run's params stale and the comparison
    vacuous-or-flaky depending on cache temperature. Fresh compiles
    are correct on every jax."""
    from jax._src import compilation_cache as _cc

    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", old)
    _cc.reset_cache()


def test_injected_crash_auto_resumes_bit_identical(
    tmp_path, _no_persistent_cache
):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    steps = 4
    # Reference: uninterrupted 4-step run.
    cfg_a = _cfg(tmp_path, "uninterrupted", steps=steps)
    mpath_a = tmp_path / "a.jsonl"
    ta = Trainer(cfg_a, sharding_mode="fsdp", metrics_path=str(mpath_a))
    ta.fit(iter(_batches(cfg_a, steps)), num_steps=steps, resume=False,
           prefetch=0)
    ta.close()
    ref = _losses(mpath_a)
    assert sorted(ref) == [1, 2, 3, 4]
    assert len({ref[s] for s in ref}) > 1, (
        "trajectory must be step-dependent for the comparison to mean "
        "anything"
    )

    # Crash run: the process dies at the top of step 3 (checkpoints at
    # 1 and 2 already on disk — checkpoint_every=1).
    cfg_b = _cfg(tmp_path, "crashed", steps=steps)
    mpath_b = tmp_path / "b.jsonl"
    faults.configure("trainer_crash:after=2")
    tb = Trainer(cfg_b, sharding_mode="fsdp", metrics_path=str(mpath_b))
    with pytest.raises(faults.FaultInjected):
        tb.fit(iter(_batches(cfg_b, steps)), num_steps=steps,
               resume=False, prefetch=0)
    # Flush the async save pipeline so "last good checkpoint" is
    # deterministic (orbax's temp+rename means a genuinely torn save
    # would be invisible to latest_step, which is the same guarantee).
    tb.ckpt.wait()
    tb.close()
    assert faults.injected_count("trainer_crash") == 1
    faults.reset()

    # The restart path: a FRESH Trainer on the same checkpoint_dir
    # auto-resumes from the last good step and replays the remaining
    # data (the loader is re-seekable; steps 1-2's batches skipped).
    mpath_c = tmp_path / "c.jsonl"
    tc = Trainer(cfg_b, sharding_mode="fsdp", metrics_path=str(mpath_c))
    start = tc.resume_if_available()
    assert start == 2, "must resume from the last completed checkpoint"
    tc.fit(iter(_batches(cfg_b, steps)[start:]), num_steps=steps,
           resume=True, prefetch=0)
    tc.close()

    got = {**_losses(mpath_b), **_losses(mpath_c)}
    assert sorted(got) == [1, 2, 3, 4]
    for s in (1, 2, 3, 4):
        assert got[s] == ref[s], (
            f"step {s}: loss {got[s]!r} != uninterrupted {ref[s]!r}"
        )
