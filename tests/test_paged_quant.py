"""int8 paged KV pool (ops/paged_kv.QuantPages): quantize-on-write /
dequantize-in-the-page-walk numerics, byte-determinism invariants
(chunk-grouping independence, COW, fetch/upload round trip), kernel
parity vs the XLA reference, and the engine-level contracts the
serving tier leans on (ragged==split within the int8 config,
cold-vs-cached byte parity, ~2x resident tokens per byte)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import generate, oryx, qwen2
from oryx_tpu.ops import paged_kv
from oryx_tpu.ops.pallas import paged_attention as ppa
from oryx_tpu.serve.pipeline import OryxInference
from oryx_tpu.serve.scheduler import ContinuousScheduler
from oryx_tpu.utils import quant


class FakeTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


@pytest.fixture(scope="module")
def pipe():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    return OryxInference(FakeTokenizer(), params, cfg)


def _quant_pool(P=8, ps=4, Hk=2, D=8):
    return paged_kv.QuantPages(
        jnp.zeros((P, ps, Hk, D), jnp.int8),
        jnp.zeros((P, ps), jnp.float32),
    )


# ---------------------------------------------------------------------------
# Op layer: write/gather numerics + byte determinism
# ---------------------------------------------------------------------------


def test_write_gather_roundtrip_error_within_envelope():
    qp = _quant_pool()
    bt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    new = jax.random.normal(jax.random.key(0), (1, 10, 2, 8))
    pool = paged_kv.write_pages(qp, new, bt, jnp.asarray([0], jnp.int32))
    got = paged_kv.gather_pages(pool, bt)[0, :10]
    err = np.abs(np.asarray(got) - np.asarray(new[0]))
    # Per-row symmetric int8: error <= scale/2 per element.
    scale = np.asarray(pool.scale).reshape(-1)[:10]
    assert (err <= scale[:, None, None] / 2 + 1e-7).all()
    # Statistical envelope matches the shared round-trip helper.
    stats = quant.roundtrip_error_stats(new[0], axis=-1)
    assert err.max() <= 10 * max(stats["max_abs_err"], 1e-6)


def test_quantization_is_chunk_grouping_independent():
    """Per-row scales make the stored bytes a pure function of each
    token's value: writing the same 10 tokens in one shot vs 2+8 vs
    5+5 lands IDENTICAL codes and scales — the invariant that keeps
    cold-vs-cached, eviction-replay and spill/reload byte-exact on
    the quantized path."""
    bt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    new = jax.random.normal(jax.random.key(1), (1, 10, 2, 8))

    def write_split(*spans):
        pool = _quant_pool()
        off = 0
        for n in spans:
            pool = paged_kv.write_pages(
                pool, new[:, off:off + n], bt,
                jnp.asarray([off], jnp.int32),
            )
            off += n
        return pool

    one = write_split(10)
    for spans in ((2, 8), (5, 5), (1, 1, 8)):
        other = write_split(*spans)
        assert jnp.array_equal(one.q, other.q)
        assert jnp.array_equal(one.scale, other.scale)


def test_packed_writer_matches_per_sequence_writer_bytes():
    bt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    new = jax.random.normal(jax.random.key(2), (1, 6, 2, 8))
    seq = paged_kv.write_pages(
        _quant_pool(), new, bt, jnp.asarray([0], jnp.int32)
    )
    packed = paged_kv.write_pages_packed(
        _quant_pool(), new[0], bt,
        jnp.zeros((6,), jnp.int32),
        jnp.arange(6, dtype=jnp.int32),
    )
    assert jnp.array_equal(seq.q, packed.q)
    assert jnp.array_equal(seq.scale, packed.scale)


def test_masked_rows_drop_codes_and_scales_together():
    bt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    new = jax.random.normal(jax.random.key(3), (1, 4, 2, 8)) + 5.0
    pool = paged_kv.write_pages(
        _quant_pool(), new, bt, jnp.asarray([0], jnp.int32),
        write_mask=jnp.asarray([False]),
    )
    assert not np.asarray(pool.q).any()
    assert not np.asarray(pool.scale).any()


def _layered_quant_pool(L=2, P=8, ps=4, Hk=2, D=8, seed=4):
    """A populated POOL-level pytree: [L, P, ...] leaves, the layout
    copy_pages/fetch_page/upload_page contract on (the per-plane
    tests above exercise the in-dispatch [P, ...] layer view)."""
    k1, k2 = jax.random.split(jax.random.key(seed))

    def mk(key):
        kq, ks = jax.random.split(key)
        return paged_kv.QuantPages(
            jax.random.randint(kq, (L, P, ps, Hk, D), -127, 128).astype(
                jnp.int8
            ),
            jax.random.uniform(ks, (L, P, ps), jnp.float32),
        )

    return {"k": mk(k1), "v": mk(k2)}


def test_cow_copies_codes_and_scales_verbatim():
    pool = _layered_quant_pool()
    out = paged_kv.copy_pages(
        pool, jnp.asarray(1, jnp.int32), jnp.asarray(6, jnp.int32)
    )
    assert jnp.array_equal(out["k"].q[:, 6], out["k"].q[:, 1])
    assert jnp.array_equal(out["k"].scale[:, 6], out["k"].scale[:, 1])
    assert jnp.array_equal(out["v"].q[:, 6], out["v"].q[:, 1])


def test_fetch_upload_page_bitwise_roundtrip():
    pool = _layered_quant_pool()
    blob = paged_kv.fetch_page(pool, 1)
    nbytes = paged_kv.host_blob_bytes(blob)
    assert nbytes > 0
    ref_q = np.asarray(pool["k"].q[:, 1]).copy()
    ref_s = np.asarray(pool["k"].scale[:, 1]).copy()
    out = paged_kv.upload_page(pool, jnp.asarray(5, jnp.int32), blob)
    assert np.array_equal(np.asarray(out["k"].q[:, 5]), ref_q)
    assert np.array_equal(np.asarray(out["k"].scale[:, 5]), ref_s)


def test_kv_pool_dtype_names():
    cfg = cfg_lib.oryx_tiny().llm
    dense = qwen2.init_paged_kv_cache(cfg, 4, 8, dtype=jnp.float32)
    assert paged_kv.kv_pool_dtype(dense) == "float32"
    q8 = qwen2.init_paged_kv_cache(
        cfg, 4, 8, dtype=jnp.float32, kv_dtype="int8"
    )
    assert paged_kv.kv_pool_dtype(q8) == "int8"
    assert q8["k"].shape == dense["k"].shape
    assert q8["k"].storage_dtype == jnp.int8
    f8 = qwen2.init_paged_kv_cache(
        cfg, 4, 8, dtype=jnp.float32, kv_dtype="fp8_e4m3"
    )
    assert paged_kv.kv_pool_dtype(f8) == "fp8_e4m3"
    with pytest.raises(ValueError, match="unknown KV storage dtype"):
        qwen2.init_paged_kv_cache(cfg, 4, 8, kv_dtype="int4")


# ---------------------------------------------------------------------------
# Kernel parity: Pallas in-walk dequant vs the XLA gather-dequant ref
# ---------------------------------------------------------------------------


def _written_quant_pool(P=16, ps=8, Hk=2, D=16, tokens=40, seed=0):
    pool = paged_kv.QuantPages(
        jnp.zeros((P, ps, Hk, D), jnp.int8),
        jnp.zeros((P, ps), jnp.float32),
    )
    maxp = -(-tokens // ps)
    bt = jnp.arange(maxp, dtype=jnp.int32)[None]
    new = jax.random.normal(jax.random.key(seed), (1, tokens, Hk, D))
    pool = paged_kv.write_pages(
        pool, new, bt, jnp.asarray([0], jnp.int32)
    )
    return pool, bt


def test_ragged_kernel_matches_reference_on_quant_pool():
    pool, bt = _written_quant_pool()
    S = 1
    bt_s = jnp.tile(bt, (S, 1))
    q = jax.random.normal(jax.random.key(9), (6, 4, 16))
    seg = jnp.zeros((6,), jnp.int32)
    pos = jnp.asarray([3, 10, 17, 25, 33, 39], jnp.int32)
    ref = paged_kv.ragged_paged_attention(q, pool, pool, bt_s, seg, pos)
    for hb in (1, 2):
        ker = ppa.ragged_paged_attention(
            q, pool, pool, bt_s, seg, pos,
            heads_per_block=hb, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(ker), np.asarray(ref), rtol=2e-6, atol=2e-6
        )


def test_decode_kernel_matches_reference_on_quant_pool():
    pool, bt = _written_quant_pool()
    q = jax.random.normal(jax.random.key(10), (1, 4, 16))
    for n in (1, 7, 40):
        kl = jnp.asarray([n], jnp.int32)
        ref = paged_kv.ragged_decode_attention(q, pool, pool, bt, kl)
        ker = ppa.ragged_decode_attention(
            q, pool, pool, bt, kl, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(ker), np.asarray(ref), rtol=2e-6, atol=2e-6
        )


def test_mixed_quant_dense_pool_rejected():
    pool, bt = _written_quant_pool()
    dense = jnp.zeros(pool.shape, jnp.float32)
    q = jax.random.normal(jax.random.key(11), (2, 4, 16))
    with pytest.raises(ValueError, match="both planes"):
        ppa.ragged_paged_attention(
            q, pool, dense, bt, jnp.zeros((2,), jnp.int32),
            jnp.asarray([1, 2], jnp.int32), interpret=True,
        )


# ---------------------------------------------------------------------------
# Driver + engine layer
# ---------------------------------------------------------------------------


def _gen(pipe, kv_dtype, ragged=False, prefill_chunk=None, seed=1):
    cfg = pipe.cfg
    H = cfg.llm.hidden_size
    emb = (
        jax.random.normal(jax.random.key(seed), (2, 12, H)) * 0.05
    ).astype(jnp.float32)
    out = generate.generate_paged(
        pipe.params["llm"], cfg.llm, cfg.generation,
        inputs_embeds=emb,
        lengths=jnp.asarray([12, 7], jnp.int32),
        max_new_tokens=8, page_size=8, chunk=4,
        compute_dtype=jnp.float32, kv_dtype=kv_dtype,
        ragged=ragged, prefill_chunk=prefill_chunk,
    )
    return np.asarray(out[0] if isinstance(out, tuple) else out)


def test_generate_paged_int8_ragged_equals_split(pipe):
    split = _gen(pipe, "int8")
    ragged = _gen(pipe, "int8", ragged=True)
    assert np.array_equal(split, ragged)


def test_generate_paged_int8_chunked_prefill_parity(pipe):
    one = _gen(pipe, "int8")
    chunked = _gen(pipe, "int8", prefill_chunk=4)
    assert np.array_equal(one, chunked)


def _boot(pipe, **kw):
    return ContinuousScheduler(
        pipe, num_slots=2, page_size=8, chunk=4, max_ctx=256,
        prefill_chunk=16, **kw,
    )


def _ask(sched, text, n=8):
    h = sched.submit({"question": text}, n, {"temperature": 0.0})
    return h.result(timeout=180)


def test_engine_int8_cold_vs_cached_byte_parity(pipe):
    sched = _boot(pipe, kv_dtype="int8")
    try:
        prompt = "cached prefix parity check " * 3
        cold = _ask(sched, prompt)
        warm = _ask(sched, prompt)
        assert cold[0] == warm[0]
        # One of the two requests spliced (suffix-only prefill).
        cached = [
            ev.get("cached_tokens", 0)
            for ev in sched.request_log.snapshot(4)
            if ev.get("status") == "ok"
        ]
        assert max(cached) > 0
        sched._check_pool_invariant()
    finally:
        sched.close()


def test_engine_int8_pool_info_gauge(pipe):
    sched = _boot(pipe, kv_dtype="int8")
    try:
        text = sched.metrics.render()
        assert 'oryx_pool_kv_dtype{kv_dtype="int8"} 1' in text
    finally:
        sched.close()


def test_int8_pool_bytes_half_of_bf16():
    """The capacity claim at its root: per-token KV bytes. int8 codes
    + per-row fp32 scales cost (Hk*D + 4) bytes vs 2*Hk*D for bf16 —
    ~2x resident tokens per HBM byte at real head geometry (the tiny
    test geometry is below 2x only because of the fixed scale)."""
    cfg = cfg_lib.oryx_tiny().llm

    def pool_bytes(kv_dtype):
        pool = qwen2.init_paged_kv_cache(
            cfg, 8, 16, dtype=jnp.bfloat16, kv_dtype=kv_dtype
        )
        return sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(pool)
        )

    dense = pool_bytes(None)
    q8 = pool_bytes("int8")
    row = cfg.num_kv_heads * cfg.head_dim
    expect = (row + 4) / (2 * row)
    assert q8 / dense == pytest.approx(expect, rel=1e-6)
    # At serving geometry (8 kv heads x 128 dims) that ratio is ~0.502.
    assert (1024 + 4) / 2048 < 0.51
