"""Worker process for tests/test_multiprocess.py (serving leg) — NOT a
pytest module.

Two processes x 4 CPU devices: tensor-parallel serving over the global
tp=8 mesh (the reference's multi-GPU `device_map` analog at multi-host
scale). Both processes run the same chat_batch and must produce
byte-identical replies; the reply text is printed for the parent to
compare across processes.

Run directly (in 2 processes):
    python tests/mp_serve_worker.py <pid> <port>
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
))
from mp_common import bootstrap  # noqa: E402

pid, jax = bootstrap()

import numpy as np  # noqa: E402

from oryx_tpu import config as cfg_lib  # noqa: E402
from oryx_tpu.config import MeshConfig  # noqa: E402

from test_serve import FakeTokenizer  # noqa: E402

from oryx_tpu.models import oryx  # noqa: E402
from oryx_tpu.parallel.mesh import build_mesh  # noqa: E402
from oryx_tpu.serve.pipeline import OryxInference  # noqa: E402

cfg = cfg_lib.oryx_tiny()
params = oryx.init_params(cfg, jax.random.key(0))

mesh = build_mesh(MeshConfig(tp=8))
pipe = OryxInference(FakeTokenizer(), params, cfg, mesh=mesh,
                     sharding_mode="tp")
leaves = jax.tree_util.tree_leaves(pipe.params)
assert any(not l.sharding.is_fully_replicated for l in leaves)

rng = np.random.default_rng(5)
img = rng.integers(0, 255, size=(40, 56, 3), dtype=np.uint8)
replies = pipe.chat_batch(
    [
        {"question": "what is this?", "images": [img]},
        {"question": "hello there"},
    ],
    max_new_tokens=4,
)
print(json.dumps({
    "mp_result": True, "pid": pid,
    "process_count": jax.process_count(),
    "replies": replies,
}), flush=True)
