"""OryxViT / packing / Dynamic Compressor tests (SURVEY.md §4 "Unit").

Key properties:
  * packed-buffer encoding == encoding each image alone (segment isolation),
  * block math parity vs HF `SiglipVisionModel` at the base resolution,
  * posemb interpolation parity vs torch F.interpolate bilinear,
  * compressor region pooling/attention correctness.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import compressor, import_hf, oryx_vit
from oryx_tpu.ops import packing

VCFG = cfg_lib.tiny_vision()  # hidden 48, heads 4, patch 14, base_grid 8


def _rand_image(rng, h_patches, w_patches):
    return rng.standard_normal(
        (h_patches * VCFG.patch_size, w_patches * VCFG.patch_size, 3)
    ).astype(np.float32)


def test_patchify_shapes_and_order():
    rng = np.random.default_rng(0)
    img = _rand_image(rng, 2, 3)
    patches, (h, w) = packing.patchify(img, VCFG.patch_size)
    assert (h, w) == (2, 3)
    assert patches.shape == (6, VCFG.patch_size**2 * 3)
    # Patch (1, 2) top-left pixel == image pixel (14, 28), channel order kept.
    np.testing.assert_array_equal(patches[5, :3], img[14, 28, :3])


def test_posemb_interp_matches_torch_bilinear():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(1)
    G, H = VCFG.base_grid, 16
    table = rng.standard_normal((G * G, H)).astype(np.float32)
    for (h, w) in [(G, G), (5, 11), (13, 3), (1, 1)]:
        coords = packing.posemb_source_coords(h, w, G)
        got = np.asarray(
            oryx_vit.interp_pos_embed(jnp.asarray(table), jnp.asarray(coords), G)
        )
        ref = (
            torch.nn.functional.interpolate(
                torch.tensor(table).reshape(1, G, G, H).permute(0, 3, 1, 2),
                size=(h, w), mode="bilinear", align_corners=False,
            )
            .permute(0, 2, 3, 1).reshape(h * w, H).numpy()
        )
        np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


def test_packed_equals_solo_encoding():
    """Two images packed together encode identically to each alone."""
    rng = np.random.default_rng(2)
    imgs = [_rand_image(rng, 3, 4), _rand_image(rng, 2, 2)]
    params = oryx_vit.init_params(VCFG, jax.random.key(0))

    def encode(image_list):
        pk = packing.pack_images(
            image_list, patch_size=VCFG.patch_size, base_grid=VCFG.base_grid,
            buckets=(64, 128, 256),
        )
        feats = oryx_vit.forward(
            params, VCFG,
            jnp.asarray(pk.patches), jnp.asarray(pk.segment_ids),
            jnp.asarray(pk.pos_coords),
        )
        return np.asarray(feats), pk

    both, pk_both = encode(imgs)
    for i, img in enumerate(imgs):
        solo, pk_solo = encode([img])
        n = pk_solo.num_patches
        packed_rows = both[pk_both.segment_ids == i + 1]
        np.testing.assert_allclose(packed_rows, solo[:n], atol=1e-4, rtol=1e-4)


def test_parity_vs_hf_siglip_base_resolution():
    """At exactly base_grid resolution (posemb identity), our packed encoder
    must match HF SiglipVisionModel (same weights via the importer)."""
    torch = pytest.importorskip("torch")
    from transformers import SiglipVisionConfig, SiglipVisionModel

    torch.manual_seed(0)
    hf_cfg = SiglipVisionConfig(
        hidden_size=VCFG.hidden_size,
        intermediate_size=VCFG.intermediate_size,
        num_hidden_layers=VCFG.num_layers,
        num_attention_heads=VCFG.num_heads,
        image_size=VCFG.base_grid * VCFG.patch_size,
        patch_size=VCFG.patch_size,
        layer_norm_eps=VCFG.layer_norm_eps,
        vision_use_head=False,
    )
    hf = SiglipVisionModel(hf_cfg).eval()
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = import_hf.import_siglip(sd, VCFG)

    rng = np.random.default_rng(3)
    img = _rand_image(rng, VCFG.base_grid, VCFG.base_grid)
    with torch.no_grad():
        ref = hf(
            torch.tensor(img).permute(2, 0, 1)[None]
        ).last_hidden_state.numpy()[0]

    pk = packing.pack_images(
        [img], patch_size=VCFG.patch_size, base_grid=VCFG.base_grid,
        buckets=(64, 128, 256),
    )
    got = oryx_vit.forward(
        params, VCFG,
        jnp.asarray(pk.patches), jnp.asarray(pk.segment_ids),
        jnp.asarray(pk.pos_coords),
    )
    np.testing.assert_allclose(
        np.asarray(got)[: pk.num_patches], ref, atol=2e-4, rtol=2e-3
    )


def test_compressor_pooling_and_shapes():
    """Factor-2 compression of a 4x4 grid: 4 queries, each pooling its 2x2
    region; identity-ish check on the pooling path."""
    rng = np.random.default_rng(4)
    ccfg = cfg_lib.CompressorConfig(num_heads=4)
    lcfg = cfg_lib.tiny_llm()
    img = _rand_image(rng, 4, 4)
    pk = packing.pack_images(
        [img], patch_size=VCFG.patch_size, base_grid=VCFG.base_grid,
        side_factors=2, buckets=(16, 64, 256),
    )
    assert pk.q_grids[0] == (2, 2)
    assert pk.num_queries == 4
    # Region ids: patch (r, c) -> region 1 + (r//2)*2 + (c//2)
    rid = pk.region_ids[: pk.num_patches].reshape(4, 4)
    assert rid[0, 0] == rid[1, 1] == 1
    assert rid[0, 2] == rid[1, 3] == 2
    assert rid[3, 3] == 4

    params = compressor.init_params(ccfg, VCFG, lcfg, jax.random.key(0))
    feats = jnp.asarray(rng.standard_normal((pk.patches.shape[0], VCFG.hidden_size)).astype(np.float32))
    out = compressor.forward(
        params, ccfg, VCFG, feats,
        jnp.asarray(pk.region_ids), jnp.asarray(pk.q_region_ids),
    )
    assert out.shape == (pk.q_region_ids.shape[0], lcfg.hidden_size)
    out = np.asarray(out)
    assert np.all(out[pk.num_queries:] == 0)  # pad rows zeroed
    assert np.all(np.isfinite(out[: pk.num_queries]))


def test_compressor_packed_equals_solo():
    rng = np.random.default_rng(5)
    ccfg = cfg_lib.CompressorConfig(num_heads=4)
    lcfg = cfg_lib.tiny_llm()
    params = compressor.init_params(ccfg, VCFG, lcfg, jax.random.key(1))
    vit_params = oryx_vit.init_params(VCFG, jax.random.key(2))
    imgs = [_rand_image(rng, 4, 4), _rand_image(rng, 2, 4)]

    def run(image_list, factors):
        pk = packing.pack_images(
            image_list, patch_size=VCFG.patch_size, base_grid=VCFG.base_grid,
            side_factors=factors, buckets=(16, 64, 256),
        )
        feats = oryx_vit.forward(
            params=vit_params, cfg=VCFG,
            patches=jnp.asarray(pk.patches),
            segment_ids=jnp.asarray(pk.segment_ids),
            pos_coords=jnp.asarray(pk.pos_coords),
        )
        out = compressor.forward(
            params, ccfg, VCFG, feats,
            jnp.asarray(pk.region_ids), jnp.asarray(pk.q_region_ids),
        )
        return np.asarray(out), pk

    both, pk_both = run(imgs, [2, 1])
    solo0, pk0 = run([imgs[0]], [2])
    solo1, pk1 = run([imgs[1]], [1])
    np.testing.assert_allclose(
        both[pk_both.q_segment_ids == 1], solo0[: pk0.num_queries],
        atol=1e-4, rtol=1e-4,
    )
    np.testing.assert_allclose(
        both[pk_both.q_segment_ids == 2], solo1[: pk1.num_queries],
        atol=1e-4, rtol=1e-4,
    )
