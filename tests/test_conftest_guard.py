"""The conftest bootstrap guard (VERDICT r5 weak 5): the hazard
decision that keeps a naive `python -m pytest tests` from sleeping
forever in axon/TPU-tunnel backend init must trip on every known
hazard and stay quiet on the sanitized environment the suite actually
runs under."""

import importlib.util
import os


def _load_hazard():
    spec = importlib.util.spec_from_file_location(
        "_oryx_conftest_under_test",
        os.path.join(os.path.dirname(__file__), "conftest.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod._axon_hazard


def test_sanitized_env_is_safe():
    hazard = _load_hazard()
    env = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}
    assert hazard(env, {}) is None
    assert hazard({}, {}) is None  # nothing set at all
    assert hazard({"JAX_PLATFORMS": ""}, {}) is None


def test_hazards_detected():
    hazard = _load_hazard()
    # axon plugin already imported (sitecustomize ran before us).
    assert "axon" in hazard({}, {"axon": object()})
    assert "axon" in hazard({}, {"axon.register": object()})
    # ...but a module merely containing "axon" in its name is fine.
    assert hazard({}, {"saxonparser": object()}) is None
    # Env that would make sitecustomize dial the tunnel.
    assert "PALLAS_AXON_POOL_IPS" in hazard(
        {"PALLAS_AXON_POOL_IPS": "10.0.0.1"}, {}
    )
    assert "JAX_PLATFORMS" in hazard({"JAX_PLATFORMS": "tpu"}, {})


def test_jax_preimport_only_hazardous_with_noncpu_backend():
    hazard = _load_hazard()
    # jax imported pre-conftest with only-CPU (or no) backends is the
    # normal re-exec'd / warm state — must NOT trip (a false positive
    # here would re-exec-loop into the fail-fast path).
    import jax  # noqa: F401 - real module, CPU backend from conftest
    import sys

    assert hazard(
        {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
        dict(sys.modules),
    ) is None
