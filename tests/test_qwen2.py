"""Qwen2 backbone parity vs HF transformers on CPU (SURVEY.md §4 "Unit").

Builds a tiny random HF `Qwen2ForCausalLM`, imports its weights through
`import_hf.import_qwen2`, and requires logits to match to fp32-CPU
tolerance. This simultaneously validates model math and the importer —
the reference's "bit-close" parity bar (BASELINE.json north_star).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import import_hf, qwen2

TINY = cfg_lib.tiny_llm(vocab_size=128)


@pytest.fixture(scope="module")
def hf_model():
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(0)
    hf_cfg = Qwen2Config(
        vocab_size=TINY.vocab_size,
        hidden_size=TINY.hidden_size,
        intermediate_size=TINY.intermediate_size,
        num_hidden_layers=TINY.num_layers,
        num_attention_heads=TINY.num_heads,
        num_key_value_heads=TINY.num_kv_heads,
        head_dim=TINY.head_dim,
        rope_theta=TINY.rope_theta,
        rms_norm_eps=TINY.rms_norm_eps,
        max_position_embeddings=TINY.max_position_embeddings,
        tie_word_embeddings=False,
        attention_dropout=0.0,
    )
    model = Qwen2ForCausalLM(hf_cfg).eval()
    return model


@pytest.fixture(scope="module")
def jx_params(hf_model):
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    return import_hf.import_qwen2(sd, TINY)


def test_logits_parity_full_sequence(hf_model, jx_params):
    import torch

    rng = np.random.default_rng(0)
    ids = rng.integers(0, TINY.vocab_size, size=(2, 17))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    got, _ = qwen2.forward(jx_params, TINY, input_ids=jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-4, rtol=2e-3)


def test_logits_parity_padded_batch(hf_model, jx_params):
    """Right-padded rows with a kv padding mask must match per-row HF runs."""
    import torch

    rng = np.random.default_rng(1)
    lens = [5, 11]
    T = max(lens)
    ids = rng.integers(1, TINY.vocab_size, size=(2, T))
    mask = np.zeros((2, T), np.int32)
    for i, l in enumerate(lens):
        ids[i, l:] = 0
        mask[i, :l] = 1
    got, _ = qwen2.forward(
        jx_params, TINY, input_ids=jnp.asarray(ids),
        kv_mask=jnp.asarray(mask),
    )
    got = np.asarray(got)
    for i, l in enumerate(lens):
        with torch.no_grad():
            ref = hf_model(torch.tensor(ids[None, i, :l])).logits.numpy()[0]
        np.testing.assert_allclose(got[i, :l], ref, atol=2e-4, rtol=2e-3)


def test_logits_parity_yi_llama_path():
    """Bias-free (Yi-34B class) geometry vs HF LlamaForCausalLM: the
    Oryx-34B backbone's parity path — GQA, no qkv bias, rms 1e-5 — at
    tiny scale. Validates model math AND the Llama-family importer."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    yi = cfg_lib.LLMConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        rope_theta=5_000_000.0, rms_norm_eps=1e-5,
        max_position_embeddings=512, attention_bias=False,
    )
    torch.manual_seed(2)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=yi.vocab_size, hidden_size=yi.hidden_size,
        intermediate_size=yi.intermediate_size,
        num_hidden_layers=yi.num_layers,
        num_attention_heads=yi.num_heads,
        num_key_value_heads=yi.num_kv_heads,
        head_dim=yi.head_dim, rope_theta=yi.rope_theta,
        rms_norm_eps=yi.rms_norm_eps,
        max_position_embeddings=yi.max_position_embeddings,
        tie_word_embeddings=False, attention_bias=False,
        attention_dropout=0.0,
    )).eval()
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = import_hf.import_qwen2(sd, yi)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, yi.vocab_size, size=(2, 13))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got, _ = qwen2.forward(params, yi, input_ids=jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-4, rtol=2e-3)


def test_kv_cache_decode_matches_full_forward(jx_params):
    """Prefill + single-token cached decode == one uncached forward."""
    rng = np.random.default_rng(2)
    B, T = 2, 13
    ids = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(B, T)))

    full, _ = qwen2.forward(jx_params, TINY, input_ids=ids)

    S = 16
    cache = qwen2.init_kv_cache(TINY, B, S, dtype=jnp.float32)
    prefill_len = T - 1
    pos = jnp.broadcast_to(jnp.arange(prefill_len, dtype=jnp.int32), (B, prefill_len))
    kv_mask = (jnp.arange(S) < prefill_len)[None, :].astype(jnp.int32)
    kv_mask = jnp.broadcast_to(kv_mask, (B, S))
    logits_p, cache = qwen2.forward(
        jx_params, TINY, input_ids=ids[:, :prefill_len], positions=pos,
        kv_cache=cache, kv_mask=kv_mask,
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(full[:, :prefill_len]),
        atol=1e-5, rtol=1e-5,
    )

    pos1 = jnp.full((B, 1), prefill_len, dtype=jnp.int32)
    kv_mask1 = (jnp.arange(S) < T)[None, :].astype(jnp.int32)
    kv_mask1 = jnp.broadcast_to(kv_mask1, (B, S))
    logits_d, _ = qwen2.forward(
        jx_params, TINY, input_ids=ids[:, prefill_len:], positions=pos1,
        kv_cache=cache, kv_mask=kv_mask1,
    )
    np.testing.assert_allclose(
        np.asarray(logits_d[:, 0]), np.asarray(full[:, -1]),
        atol=1e-5, rtol=1e-5,
    )


def test_tied_embeddings_and_no_bias():
    cfg = cfg_lib.LLMConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, num_kv_heads=1, head_dim=16, tie_word_embeddings=True,
        attention_bias=False,
    )
    params = qwen2.init_params(cfg, jax.random.key(0))
    assert "lm_head" not in params
    assert "bias" not in params["layers"]["q_proj"]
    ids = jnp.zeros((1, 4), jnp.int32)
    logits, _ = qwen2.forward(params, cfg, input_ids=ids)
    assert logits.shape == (1, 4, 64)


def test_export_roundtrip():
    params = qwen2.init_params(TINY, jax.random.key(1))
    sd = import_hf.export_qwen2(params, TINY)
    back = import_hf.import_qwen2(sd, TINY)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6),
        params, back,
    )
