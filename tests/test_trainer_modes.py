"""Trainer sharding-mode coverage on the 8-device CPU mesh: fsdp (ZeRO-3),
zero2 (params replicated, optimizer state sharded), ddp (all replicated)
— SURVEY.md §2b parallelism inventory."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.constants import IGNORE_INDEX, IMAGE_TOKEN_INDEX
from oryx_tpu.models import splice
from oryx_tpu.ops import packing
from oryx_tpu.train.trainer import Trainer


def _cfg(tmp_path, mode_dir):
    cfg = cfg_lib.oryx_tiny()
    return dataclasses.replace(
        cfg,
        mesh=cfg_lib.MeshConfig(dp=2, fsdp=4, tp=1, sp=1),
        train=dataclasses.replace(
            cfg.train,
            num_train_steps=1, log_every=1, checkpoint_every=100,
            checkpoint_dir=str(tmp_path / mode_dir),
        ),
    )


def _batch(cfg, n=8):
    rng = np.random.default_rng(0)
    p = cfg.vision.patch_size
    imgs = [
        rng.standard_normal((2 * p, 2 * p, 3)).astype(np.float32)
        for _ in range(n)
    ]
    packed = packing.pack_images(
        imgs, patch_size=p, base_grid=cfg.vision.base_grid,
        side_factors=1, buckets=(64, 256),
    )
    slots = splice.query_slots(packed)
    ids, labels = [], []
    for _ in range(n):
        row = np.concatenate([[5, IMAGE_TOKEN_INDEX], rng.integers(3, 500, 6)])
        lab = np.full(row.shape, IGNORE_INDEX, np.int64)
        lab[-6:] = row[-6:]
        ids.append(row)
        labels.append(lab)
    mm = splice.build_mm_batch(ids, slots, labels=labels, buckets=(16, 64))
    return {
        "patches": packed.patches, "segment_ids": packed.segment_ids,
        "pos_coords": packed.pos_coords, "region_ids": packed.region_ids,
        "q_region_ids": packed.q_region_ids, "token_ids": mm.token_ids,
        "visual_idx": mm.visual_idx, "is_visual": mm.is_visual,
        "attn_mask": mm.attn_mask, "positions": mm.positions,
        "labels": mm.labels,
    }


def test_trainer_checkpoint_resume(tmp_path):
    """Failure posture (SURVEY.md §5): a fresh Trainer on the same
    checkpoint_dir resumes from the saved step and continues — the
    crashed-pod restart path."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    cfg = _cfg(tmp_path, "resume")
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, checkpoint_every=1)
    )
    b = _batch(cfg)
    t1 = Trainer(cfg, sharding_mode="fsdp")
    s1 = t1.fit(iter([b]), num_steps=1, resume=False, prefetch=0)
    assert int(jax.device_get(s1.step)) == 1

    t2 = Trainer(cfg, sharding_mode="fsdp")
    start = t2.resume_if_available()
    assert start == 1
    # Resumed params equal the step-1 params, not a fresh init.
    for a, c in zip(
        jax.tree_util.tree_leaves(s1.params),
        jax.tree_util.tree_leaves(t2.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    s2 = t2.fit(iter([b]), num_steps=2, resume=True, prefetch=0)
    assert int(jax.device_get(s2.step)) == 2


@pytest.mark.parametrize("mode", ["fsdp", "zero2", "ddp"])
def test_trainer_mode_one_step(tmp_path, mode):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    cfg = _cfg(tmp_path, mode)
    trainer = Trainer(cfg, sharding_mode=mode)
    batch = _batch(cfg)
    state = trainer.fit(iter([batch]), num_steps=1, resume=False,
                        prefetch=0)
    assert int(jax.device_get(state.step)) == 1
    # Param placement matches the mode: fsdp shards embed over the mesh;
    # zero2/ddp replicate params.
    embed = state.params["llm"]["embed"]["weight"]
    if mode == "fsdp":
        assert not embed.sharding.is_fully_replicated
    else:
        assert embed.sharding.is_fully_replicated
    # Optimizer moments shard over fsdp in both fsdp AND zero2 (ZeRO-2 =
    # replicated params + partitioned optimizer state); ddp replicates.
    embed_shape = embed.shape
    mu_like = [
        leaf for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if getattr(leaf, "shape", None) == embed_shape
    ]
    assert mu_like, "no optimizer moment matching embed shape"
    if mode in ("fsdp", "zero2"):
        assert any(not m.sharding.is_fully_replicated for m in mu_like)
    else:
        assert all(m.sharding.is_fully_replicated for m in mu_like)


def test_trainer_step_traces_and_phase_metrics(tmp_path):
    """Observability: each step lands in the trainer's flight recorder
    with data/h2d/step_dispatch/device_sync phase spans, and the phase
    seconds ride the MetricLogger JSONL record."""
    import json

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    cfg = _cfg(tmp_path, "traced")
    b = _batch(cfg)
    mpath = tmp_path / "metrics.jsonl"
    t = Trainer(cfg, sharding_mode="fsdp", metrics_path=str(mpath))
    t.fit(iter([b]), num_steps=1, resume=False, prefetch=0)

    traces = t.tracer.traces()
    assert len(traces) == 1
    tr = traces[0]
    assert tr.kind == "train_step" and tr.done
    assert tr.meta["step"] == 1
    names = [s.name for s in tr.spans]
    for want in ("data", "h2d", "step_dispatch", "device_sync"):
        assert want in names, names
    assert all(s.dur_ns is not None for s in tr.spans)

    rec = json.loads(mpath.read_text().splitlines()[-1])
    for key in ("data_s", "dispatch_s", "sync_s"):
        assert key in rec and rec[key] >= 0
    # Chrome export of a step trace is loadable JSON with X events.
    body = t.tracer.chrome_trace([tr])
    assert any(e.get("ph") == "X" for e in body["traceEvents"])
    json.dumps(body)


def test_trainer_rejects_packed_text_under_ring():
    """VERDICT item 4 (satellite): the ring x packed-text trap fails
    fast at the trainer boundary with an actionable message instead of
    dying deep in jit (or training silently wrong)."""
    import numpy as np

    from oryx_tpu.train.trainer import validate_train_batch

    packed = {"text_segment_ids": np.ones((1, 2, 8), np.int32)}
    for impl in ("ring", "ring_flash"):
        cfg = dataclasses.replace(cfg_lib.oryx_tiny(), attn_impl=impl)
        with pytest.raises(ValueError, match="no.*segment support"):
            validate_train_batch(cfg, packed)
    # Packed text under xla/pallas is fine; ring without packing is fine.
    validate_train_batch(cfg_lib.oryx_tiny(), packed)
    validate_train_batch(
        dataclasses.replace(cfg_lib.oryx_tiny(), attn_impl="ring_flash"),
        {"token_ids": np.zeros((1, 2, 8), np.int32)},
    )
