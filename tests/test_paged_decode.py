"""Paged KV cache + chunked decode: allocator behavior, ragged decode
attention (XLA reference and Pallas twin), and greedy bit-parity between
the dense `_decode_while` path and the paged chunked path — with and
without prefix KV reuse."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import generate as gen_lib
from oryx_tpu.models import qwen2
from oryx_tpu.ops import attention as att_lib
from oryx_tpu.ops import paged_kv


# ---------------------------------------------------------------------------
# Allocator
# ---------------------------------------------------------------------------


def test_allocator_alloc_free_recycle():
    a = paged_kv.PageAllocator(4, 8)
    assert a.num_free == 4 and a.sentinel == 4
    p1 = a.alloc(2)
    p2 = a.alloc(2)
    assert sorted(p1 + p2) == [0, 1, 2, 3]
    assert a.num_free == 0
    with pytest.raises(paged_kv.OutOfPagesError):
        a.alloc(1)
    a.free(p1)
    # LIFO recycling: freshly freed pages come back first.
    assert a.alloc(2) == p1
    a.free(p1)
    a.free(p2)
    assert a.num_free == 4
    with pytest.raises(ValueError):
        a.free(p2)  # double free
    assert a.pages_for(0) == 0
    assert a.pages_for(1) == 1
    assert a.pages_for(8) == 1
    assert a.pages_for(9) == 2


def test_allocator_all_or_nothing():
    a = paged_kv.PageAllocator(3, 4)
    a.alloc(2)
    with pytest.raises(paged_kv.OutOfPagesError):
        a.alloc(2)
    assert a.num_free == 1  # the failed alloc leaked nothing


# ---------------------------------------------------------------------------
# Page I/O + ragged attention vs the dense reference
# ---------------------------------------------------------------------------


def _ragged_fixture(seed=0, B=3, Hq=4, Hk=2, D=16, ps=8, maxp=4, P=16):
    """Pages + block tables + an equivalent dense [B, K, Hk, D] view."""
    rng = np.random.default_rng(seed)
    lengths = np.array([5, 17, maxp * ps], np.int32)[:B]
    alloc = paged_kv.PageAllocator(P, ps)
    bt = np.full((B, maxp), alloc.sentinel, np.int32)
    k_pool = rng.standard_normal((P, ps, Hk, D)).astype(np.float32)
    v_pool = rng.standard_normal((P, ps, Hk, D)).astype(np.float32)
    K = maxp * ps
    k_dense = np.zeros((B, K, Hk, D), np.float32)
    v_dense = np.zeros((B, K, Hk, D), np.float32)
    for b in range(B):
        pages = alloc.alloc(alloc.pages_for(int(lengths[b])))
        bt[b, : len(pages)] = pages
        for s in range(int(lengths[b])):
            k_dense[b, s] = k_pool[pages[s // ps], s % ps]
            v_dense[b, s] = v_pool[pages[s // ps], s % ps]
    q = rng.standard_normal((B, 1, Hq, D)).astype(np.float32)
    return q, k_pool, v_pool, bt, lengths, k_dense, v_dense


def test_ragged_decode_attention_matches_dense():
    q, kp, vp, bt, lengths, kd, vd = _ragged_fixture()
    K = kd.shape[1]
    kv_mask = (np.arange(K)[None] < lengths[:, None]).astype(np.int32)
    ref = att_lib.attention(
        jnp.asarray(q), jnp.asarray(kd), jnp.asarray(vd), causal=True,
        q_positions=jnp.asarray(lengths - 1)[:, None],
        kv_mask=jnp.asarray(kv_mask),
    )
    got = paged_kv.ragged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(lengths),
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_pallas_paged_decode_matches_reference():
    from oryx_tpu.ops.pallas import paged_attention as ppa

    q, kp, vp, bt, lengths, _, _ = _ragged_fixture(seed=3)
    ref = paged_kv.ragged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(lengths),
    )
    got = ppa.ragged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(lengths),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-6, rtol=2e-6
    )


def test_write_pages_masks_and_sentinels():
    rng = np.random.default_rng(1)
    P, ps, Hk, D = 4, 4, 2, 8
    alloc = paged_kv.PageAllocator(P, ps)
    bt = np.full((2, 2), alloc.sentinel, np.int32)
    bt[0, :2] = alloc.alloc(2)
    bt[1, :1] = alloc.alloc(1)  # row 1 holds ONE page: slots >= 4 drop
    pool = jnp.zeros((P, ps, Hk, D), jnp.float32)
    new = jnp.asarray(rng.standard_normal((2, 3, Hk, D)), jnp.float32)
    out = paged_kv.write_pages(
        pool, new, jnp.asarray(bt), jnp.asarray([2, 3], jnp.int32)
    )
    g = paged_kv.gather_pages(out, jnp.asarray(bt))
    # Row 0: slots 2..4 all covered.
    np.testing.assert_array_equal(np.asarray(g)[0, 2:5], np.asarray(new)[0])
    # Row 1: slot 3 lands, slots 4..5 routed through the sentinel drop.
    np.testing.assert_array_equal(np.asarray(g)[1, 3], np.asarray(new)[1, 0])
    untouched = [p for p in range(P) if p not in list(bt[0]) + list(bt[1])]
    for p in untouched:
        np.testing.assert_array_equal(np.asarray(out)[p], 0.0)
    # write_mask False rows drop everything.
    out2 = paged_kv.write_pages(
        out, new * 7, jnp.asarray(bt), jnp.asarray([2, 3], jnp.int32),
        write_mask=jnp.asarray([False, False]),
    )
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(out))


# ---------------------------------------------------------------------------
# Greedy parity: dense while-loop decode vs paged chunked decode
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_llm():
    cfg = cfg_lib.tiny_llm(vocab_size=128)
    params = qwen2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _embed(params, ids):
    return params["embed"]["weight"][jnp.asarray(ids)]


def test_paged_greedy_parity_mixed_lengths(tiny_llm):
    cfg, params = tiny_llm
    gcfg = cfg_lib.GenerationConfig(temperature=0.0, eos_token_id=7)
    rng = np.random.default_rng(0)
    B, Tb, max_new, cache_len = 3, 16, 12, 32
    lengths = np.array([5, 11, 16], np.int32)
    ids = rng.integers(1, 128, size=(B, Tb)).astype(np.int32)
    toks, num, fin = gen_lib.generate(
        params, cfg, gcfg, inputs_embeds=_embed(params, ids),
        lengths=jnp.asarray(lengths), max_new_tokens=max_new,
        cache_len=cache_len,
    )
    # kv_capacity == the dense cache_len: identical fp32 reductions,
    # masked kv columns contribute exact zeros either way → BIT parity.
    ptoks, pnum, pfin = gen_lib.generate_paged(
        params, cfg, gcfg, inputs_embeds=_embed(params, ids),
        lengths=lengths, max_new_tokens=max_new, page_size=8, chunk=4,
        kv_capacity=cache_len,
    )
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ptoks))
    np.testing.assert_array_equal(np.asarray(num), np.asarray(pnum))
    np.testing.assert_array_equal(np.asarray(fin), np.asarray(pfin))


def test_paged_greedy_parity_with_stop_sequences(tiny_llm):
    """Stop-sequence rows must freeze identically on both paths: run
    dense once, turn its second emitted token into a stop sequence, and
    demand bit-equal tokens AND finish accounting."""
    cfg, params = tiny_llm
    gcfg = cfg_lib.GenerationConfig(temperature=0.0, eos_token_id=7)
    rng = np.random.default_rng(2)
    B, Tb, max_new, cache_len = 2, 16, 12, 32
    lengths = np.array([9, 14], np.int32)
    ids = rng.integers(1, 128, size=(B, Tb)).astype(np.int32)
    toks, _, _ = gen_lib.generate(
        params, cfg, gcfg, inputs_embeds=_embed(params, ids),
        lengths=jnp.asarray(lengths), max_new_tokens=max_new,
        cache_len=cache_len,
    )
    stop = np.full((1, 4), -1, np.int32)
    stop[0, -1] = int(np.asarray(toks)[0, 1])  # fires early on row 0
    stop = jnp.asarray(stop)
    args = dict(
        inputs_embeds=_embed(params, ids), max_new_tokens=max_new,
        stop_sequences=stop,
    )
    toks, num, fin = gen_lib.generate(
        params, cfg, gcfg, lengths=jnp.asarray(lengths),
        cache_len=cache_len, **args,
    )
    ptoks, pnum, pfin = gen_lib.generate_paged(
        params, cfg, gcfg, lengths=lengths, page_size=8, chunk=4,
        kv_capacity=cache_len, **args,
    )
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ptoks))
    np.testing.assert_array_equal(np.asarray(num), np.asarray(pnum))
    np.testing.assert_array_equal(np.asarray(fin), np.asarray(pfin))
    assert bool(np.asarray(fin)[0])  # the stop actually fired


def test_paged_greedy_parity_prefix_reuse(tiny_llm):
    """Two-turn conversation: turn 2 prefills only the suffix against
    the turn-1 KV (dense kv_cache/start vs paged state/start) — token
    ids must stay bit-identical."""
    cfg, params = tiny_llm
    gcfg = cfg_lib.GenerationConfig(temperature=0.0, eos_token_id=7)
    rng = np.random.default_rng(1)
    max_new, cache_len = 8, 64
    ids1 = rng.integers(1, 128, size=(1, 16)).astype(np.int32)
    L1 = 9
    t1, n1, _, cache = gen_lib.generate(
        params, cfg, gcfg, inputs_embeds=_embed(params, ids1),
        lengths=jnp.asarray([L1], np.int32), max_new_tokens=max_new,
        cache_len=cache_len, return_cache=True,
    )
    pt1, pn1, _, state = gen_lib.generate_paged(
        params, cfg, gcfg, inputs_embeds=_embed(params, ids1),
        lengths=np.asarray([L1]), max_new_tokens=max_new, page_size=8,
        chunk=4, kv_capacity=cache_len, num_pages=8, return_state=True,
    )
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(pt1))
    # Turn 2: keep prompt + generated KV, append a 6-token suffix.
    common = L1 + int(np.asarray(n1)[0])
    suf = rng.integers(1, 128, size=(1, 8)).astype(np.int32)
    L2 = common + 6
    t2, n2, f2 = gen_lib.generate(
        params, cfg, gcfg, inputs_embeds=_embed(params, suf),
        lengths=jnp.asarray([L2], np.int32), max_new_tokens=max_new,
        cache_len=cache_len, kv_cache=cache,
        start=jnp.asarray(common, jnp.int32),
    )
    pt2, pn2, pf2 = gen_lib.generate_paged(
        params, cfg, gcfg, inputs_embeds=_embed(params, suf),
        lengths=np.asarray([L2]), max_new_tokens=max_new, page_size=8,
        chunk=4, kv_capacity=cache_len, state=state,
        start=np.asarray([common]),
    )
    np.testing.assert_array_equal(np.asarray(t2), np.asarray(pt2))
    np.testing.assert_array_equal(np.asarray(n2), np.asarray(pn2))
    np.testing.assert_array_equal(np.asarray(f2), np.asarray(pf2))


def test_generate_paged_ragged_pool_sizing(tiny_llm):
    """The default pool is the exact ragged need — a short row costs its
    own pages, not the batch max (the perf claim behind the change)."""
    cfg, params = tiny_llm
    gcfg = cfg_lib.GenerationConfig(temperature=0.0, eos_token_id=7)
    rng = np.random.default_rng(4)
    ids = rng.integers(1, 128, size=(2, 32)).astype(np.int32)
    lengths = np.array([4, 32], np.int32)
    _, _, _, state = gen_lib.generate_paged(
        params, cfg, gcfg, inputs_embeds=_embed(params, ids),
        lengths=lengths, max_new_tokens=8, page_size=8, chunk=8,
        kv_capacity=64, return_state=True,
    )
    # ceil((4+8)/8)=2 + ceil((32+8)/8)=5 pages, vs 2*8 for dense capacity.
    assert state.allocator.num_pages == 7
    assert state.allocator.num_free == 0


def test_paged_decode_pallas_matches_xla(tiny_llm):
    """The chunked decode with attn_impl=pallas (in-place page reads via
    the Pallas kernel, interpret mode on CPU) emits the same greedy
    tokens as the gather-based XLA reference path."""
    cfg, params = tiny_llm
    gcfg = cfg_lib.GenerationConfig(temperature=0.0, eos_token_id=7)
    rng = np.random.default_rng(5)
    ids = rng.integers(1, 128, size=(2, 16)).astype(np.int32)
    lengths = np.array([7, 13], np.int32)
    common = dict(
        inputs_embeds=_embed(params, ids), lengths=lengths,
        max_new_tokens=6, page_size=8, chunk=2, kv_capacity=32,
    )
    xt, xn, xf = gen_lib.generate_paged(
        params, cfg, gcfg, attn_impl="xla", **common
    )
    pt, pn, pf = gen_lib.generate_paged(
        params, cfg, gcfg, attn_impl="pallas", **common
    )
    np.testing.assert_array_equal(np.asarray(xt), np.asarray(pt))
    np.testing.assert_array_equal(np.asarray(xn), np.asarray(pn))


# ---------------------------------------------------------------------------
# Samplers
# ---------------------------------------------------------------------------


def test_sample_token_top_k_clamps_to_vocab():
    """Regression: top_k >= vocab_size used to index out of range in
    jnp.sort(logits)[:, -top_k]; it must behave as 'keep everything'."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((2, 8)), jnp.float32)
    key = jax.random.key(0)
    huge = gen_lib.sample_token(
        logits, key, temperature=0.7, top_p=1.0, top_k=50
    )
    # Same key on purpose: the test asserts the three top_k settings
    # draw IDENTICAL tokens, which only holds under identical RNG.
    nofilter = gen_lib.sample_token(  # oryxlint: disable=key-linearity
        logits, key, temperature=0.7, top_p=1.0, top_k=0
    )
    exact = gen_lib.sample_token(  # oryxlint: disable=key-linearity
        logits, key, temperature=0.7, top_p=1.0, top_k=8
    )
    np.testing.assert_array_equal(np.asarray(huge), np.asarray(nofilter))
    np.testing.assert_array_equal(np.asarray(huge), np.asarray(exact))


def test_sample_token_rows_per_row_behavior():
    rng = np.random.default_rng(0)
    V = 16
    logits = jnp.asarray(rng.standard_normal((3, V)), jnp.float32)
    keys = jax.random.split(jax.random.key(1), 3)
    # Row 0 greedy, row 1 heavily top-k-1 (=> argmax too), row 2 free.
    out = gen_lib.sample_token_rows(
        logits, keys,
        temperature=jnp.asarray([0.0, 1.0, 1.0]),
        top_p=jnp.asarray([1.0, 1.0, 1.0]),
        top_k=jnp.asarray([0, 1, 0]),
    )
    assert int(out[0]) == int(jnp.argmax(logits[0]))
    assert int(out[1]) == int(jnp.argmax(logits[1]))
    assert 0 <= int(out[2]) < V
    # A row's draw is independent of its neighbors: same row alone gives
    # the same token (continuous-batching invariant).
    solo = gen_lib.sample_token_rows(
        logits[2:], keys[2:],
        temperature=jnp.asarray([1.0]),
        top_p=jnp.asarray([1.0]),
        top_k=jnp.asarray([0]),
    )
    assert int(solo[0]) == int(out[2])
    # top_k above V clamps rather than erroring: same keys on purpose —
    # the assertion is that clamped and unfiltered draw IDENTICALLY.
    clamped = gen_lib.sample_token_rows(  # oryxlint: disable=key-linearity
        logits, keys,
        temperature=jnp.asarray([1.0, 1.0, 1.0]),
        top_p=jnp.asarray([1.0, 1.0, 1.0]),
        top_k=jnp.asarray([V + 50, V + 50, V + 50]),
    )
    unfiltered = gen_lib.sample_token_rows(  # oryxlint: disable=key-linearity
        logits, keys,
        temperature=jnp.asarray([1.0, 1.0, 1.0]),
        top_p=jnp.asarray([1.0, 1.0, 1.0]),
        top_k=jnp.asarray([0, 0, 0]),
    )
    np.testing.assert_array_equal(np.asarray(clamped),
                                  np.asarray(unfiltered))
