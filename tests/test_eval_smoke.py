"""The committed smoke benchmark (assets/smoke_eval) through the REAL
CLI path: scripts/make_smoke_eval.py builds a model dir with an on-disk
HF tokenizer, then eval.harness.main loads the pipeline from disk, runs
batched decode over the committed media, scores, and writes the result
JSON (SURVEY.md §3.5; VERDICT r3 next-round #6)."""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ASSETS = os.path.join(REPO, "assets", "smoke_eval")


def test_committed_task_schema():
    task = os.path.join(ASSETS, "task.jsonl")
    with open(task) as f:
        records = [json.loads(l) for l in f if l.strip()]
    assert len(records) == 8
    kinds = {r["meta"]["kind"] for r in records}
    assert kinds == {"image", "video"}
    for r in records:
        assert r["answer"] in "ABCD"
        assert len(r["options"]) == 4
        media = r.get("image") or r.get("video")
        assert os.path.exists(os.path.join(ASSETS, media)), media


@pytest.mark.slow
def test_smoke_eval_cli_end_to_end(tmp_path, capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "make_smoke_eval", os.path.join(REPO, "scripts", "make_smoke_eval.py")
    )
    make_smoke_eval = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(make_smoke_eval)

    model_dir = make_smoke_eval.build_model_dir(str(tmp_path))
    from oryx_tpu.eval import harness

    out = tmp_path / "result.json"
    harness.main([
        "--model-path", model_dir,
        "--task", os.path.join(ASSETS, "task.jsonl"),
        "--media-root", ASSETS,
        "--num-frames", "4",
        "--max-new-tokens", "4",
        "--by", "kind",
        "--output", str(out),
    ])
    printed = capsys.readouterr().out
    summary = json.loads(printed.strip().splitlines()[-1])
    assert summary["n"] == 8
    assert set(summary["by_kind"]) == {"image", "video"}
    result = json.loads(out.read_text())
    assert result["num_total"] == 8
    assert len(result["records"]) == 8
    ids = {r["id"] for r in result["records"]}
    assert ids == {f"smoke-{i}" for i in range(8)}
