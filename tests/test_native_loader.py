"""Native loader tests: C++ fused preprocess parity vs the numpy reference
path, batch thread-pool writes into the packed buffer, and the
pack_raw_images native/fallback equivalence (SURVEY.md §2a: the reference's
native data-loader floor; native/loader.cpp is our equivalent)."""

import numpy as np
import pytest

from oryx_tpu.data import mm_utils, native_loader
from oryx_tpu.ops import packing

pytestmark = pytest.mark.skipif(
    not native_loader.is_available(),
    reason="native loader not built (g++ unavailable?)",
)


def _numpy_reference(img, patch, max_patches):
    pre = mm_utils.preprocess_image(img, patch, max_patches)
    return packing.patchify(pre, patch)


@pytest.mark.parametrize("dtype", ["uint8", "float32"])
@pytest.mark.parametrize("hw", [(28, 28), (37, 51), (100, 40)])
def test_preprocess_parity_vs_numpy(dtype, hw):
    rng = np.random.default_rng(0)
    if dtype == "uint8":
        img = rng.integers(0, 255, size=(*hw, 3), dtype=np.uint8)
    else:
        img = rng.standard_normal((*hw, 3)).astype(np.float32)
    patch = 14
    ref, (h, w) = _numpy_reference(img, patch, 4096)
    oh, ow = mm_utils.resize_to_patch_grid(hw, patch, 4096)
    got = native_loader.preprocess_image(
        img, (oh, ow), patch, mm_utils.IMAGE_MEAN, mm_utils.IMAGE_STD
    )
    assert got.shape == ref.shape == (h * w, patch * patch * 3)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_preprocess_with_downscale_cap():
    rng = np.random.default_rng(1)
    img = rng.integers(0, 255, size=(300, 200, 3), dtype=np.uint8)
    patch, cap = 14, 64
    ref, grid = _numpy_reference(img, patch, cap)
    oh, ow = mm_utils.resize_to_patch_grid((300, 200), patch, cap)
    got = native_loader.preprocess_image(
        img, (oh, ow), patch, mm_utils.IMAGE_MEAN, mm_utils.IMAGE_STD
    )
    assert grid[0] * grid[1] <= cap
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_batch_preprocess_into_shared_buffer():
    rng = np.random.default_rng(2)
    patch = 14
    imgs = [
        rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8)
        for h, w in [(28, 28), (42, 28), (28, 56)]
    ]
    hws = [mm_utils.resize_to_patch_grid(i.shape[:2], patch, 4096)
           for i in imgs]
    rows = [(oh // patch) * (ow // patch) for oh, ow in hws]
    buf = np.zeros((sum(rows) + 5, patch * patch * 3), np.float32)
    offs = np.cumsum([0] + rows[:-1]).tolist()
    outs = [buf[o : o + r] for o, r in zip(offs, rows)]
    native_loader.batch_preprocess(
        imgs, hws, patch, mm_utils.IMAGE_MEAN, mm_utils.IMAGE_STD,
        outs=outs, num_threads=3,
    )
    for img, o, r in zip(imgs, offs, rows):
        ref, _ = _numpy_reference(img, patch, 4096)
        np.testing.assert_allclose(buf[o : o + r], ref, rtol=1e-4, atol=1e-4)
    assert np.all(buf[sum(rows):] == 0)  # no overrun


def test_pack_raw_images_matches_fallback(monkeypatch):
    rng = np.random.default_rng(3)
    imgs = [
        rng.integers(0, 255, size=(60, 45, 3), dtype=np.uint8),
        rng.integers(0, 255, size=(28, 90, 3), dtype=np.uint8),
    ]
    kw = dict(patch_size=14, base_grid=8, side_factors=[1, 2],
              max_patches=[16, 16], buckets=(64, 256))
    native = packing.pack_raw_images(imgs, **kw)
    monkeypatch.setattr(native_loader, "is_available", lambda: False)
    fallback = packing.pack_raw_images(imgs, **kw)
    np.testing.assert_allclose(
        native.patches, fallback.patches, rtol=1e-4, atol=1e-4
    )
    for field in ("segment_ids", "region_ids", "pos_coords",
                  "q_segment_ids", "q_region_ids"):
        np.testing.assert_array_equal(
            getattr(native, field), getattr(fallback, field)
        )
    assert native.grids == fallback.grids


def test_prefetch_iterator_order_and_errors():
    from oryx_tpu.train.data import PrefetchIterator

    assert list(PrefetchIterator(iter(range(7)), depth=2)) == list(range(7))

    def boom():
        yield 1
        raise RuntimeError("decode failed")

    it = PrefetchIterator(boom(), depth=1)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_prefetch_close_stops_infinite_producer():
    import itertools

    from oryx_tpu.train.data import PrefetchIterator

    it = PrefetchIterator(itertools.count(), depth=1)
    assert next(it) == 0
    it.close()
    assert not it._thread.is_alive()


def test_pack_raw_images_mixed_channels_raises():
    rng = np.random.default_rng(4)
    imgs = [
        rng.integers(0, 255, size=(28, 28, 3), dtype=np.uint8),
        rng.integers(0, 255, size=(28, 28, 4), dtype=np.uint8),
    ]
    with pytest.raises(ValueError, match="channels"):
        packing.pack_raw_images(
            imgs, patch_size=14, base_grid=8, buckets=(64, 256)
        )


def test_pack_raw_images_text_only_batch():
    packed = packing.pack_raw_images(
        [], patch_size=14, base_grid=8, buckets=(64, 256)
    )
    assert packed.num_patches == 0 and packed.num_queries == 0
    assert packed.patches.shape == (64, 14 * 14 * 3)
    assert np.all(packed.segment_ids == 0)
    assert packed.grids == []
