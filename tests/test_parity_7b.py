"""Full-geometry parity hardening (VERDICT r2 #5; BASELINE logit-parity
row): random-weight logits parity vs HF transformers at the EXACT Oryx-7B
backbone width — hidden 3584, 28 q / 4 kv heads (group 7), head_dim 128,
vocab 152064, Qwen2 attention bias — at reduced depth (2 layers), plus a
bf16-vs-fp32 drift bound at the same width.

Tolerances are pinned from measurement on this geometry (fp32 max abs
2.0e-5; bf16 max log-prob drift 0.102, top-1 agreement 1.0) with ~2-10x
headroom.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import import_hf, qwen2

CFG = dataclasses.replace(cfg_lib.qwen2_7b(), num_layers=2)


@pytest.fixture(scope="module")
def seven_b(  # noqa: C901 - fixture builds both frameworks' models once
):
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(0)
    hf_cfg = Qwen2Config(
        vocab_size=CFG.vocab_size,
        hidden_size=CFG.hidden_size,
        intermediate_size=CFG.intermediate_size,
        num_hidden_layers=CFG.num_layers,
        num_attention_heads=CFG.num_heads,
        num_key_value_heads=CFG.num_kv_heads,
        head_dim=CFG.head_dim,
        rope_theta=CFG.rope_theta,
        rms_norm_eps=CFG.rms_norm_eps,
        max_position_embeddings=CFG.max_position_embeddings,
        tie_word_embeddings=False,
        attention_dropout=0.0,
    )
    model = Qwen2ForCausalLM(hf_cfg).eval()
    ids = np.random.default_rng(0).integers(0, CFG.vocab_size, size=(1, 9))
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.numpy()
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    del model
    jx = import_hf.import_qwen2(sd, CFG)
    del sd
    return ids, ref, jx


@pytest.mark.slow
def test_logits_parity_7b_width(seven_b):
    ids, ref, jx = seven_b
    got, _ = qwen2.forward(jx, CFG, input_ids=jnp.asarray(ids))
    np.testing.assert_allclose(
        np.asarray(got), ref, atol=2e-4, rtol=2e-3
    )


@pytest.mark.slow
def test_bf16_drift_bound_7b_width(seven_b):
    """bf16 compute must stay within a bounded drift of fp32: log-prob
    max-abs < 0.25 and >= 99% greedy-token agreement."""
    ids, _, jx = seven_b
    got32, _ = qwen2.forward(jx, CFG, input_ids=jnp.asarray(ids))
    gotbf, _ = qwen2.forward(
        jx, CFG, input_ids=jnp.asarray(ids), compute_dtype=jnp.bfloat16
    )
    lg32 = np.asarray(jax.nn.log_softmax(got32))
    lgbf = np.asarray(jax.nn.log_softmax(gotbf.astype(jnp.float32)))
    assert np.abs(lgbf - lg32).max() < 0.25
    agree = (
        np.asarray(gotbf).argmax(-1) == np.asarray(got32).argmax(-1)
    ).mean()
    assert agree >= 0.99
