"""utils/trace.py: span recording, context propagation, Chrome/JSONL
export, flight-recorder bounds, and the stall watchdog's
exactly-one-dump-per-stall contract."""

import io
import json
import threading
import time

import pytest

from oryx_tpu.utils import trace as trace_lib


def test_span_nesting_and_parents():
    tr = trace_lib.Trace("request", label="t")
    with tr.span("outer"):
        with tr.span("inner", detail=7):
            pass
        tr.event("marker")
    tr.add_complete("tail", trace_lib.now_ns(), 1000)
    names = [s.name for s in tr.spans]
    assert names == ["outer", "inner", "marker", "tail"]
    outer, inner, marker, tail = tr.spans
    assert outer.parent is None
    assert inner.parent == 0 and inner.args == {"detail": 7}
    assert marker.parent == 0 and marker.dur_ns == 0
    assert tail.parent is None and tail.dur_ns == 1000
    assert all(s.dur_ns is not None for s in tr.spans)
    assert inner.start_ns >= outer.start_ns


def test_cross_scope_begin_end_and_finish_closes_open_spans():
    tr = trace_lib.Trace("request")
    h = tr.begin("queue_wait")
    assert tr.spans[h].dur_ns is None  # still open
    tr.end(h)
    assert tr.spans[h].dur_ns is not None
    h2 = tr.begin("admission")
    tr.finish(finish_reason="stop")
    assert tr.done and tr.spans[h2].dur_ns is not None
    assert tr.meta["finish_reason"] == "stop"
    assert tr.summary()["done"] is True


def test_chrome_export_shape():
    tr = trace_lib.Trace("request", label="x")
    with tr.span("prefill", tokens=5):
        pass
    tr.finish()
    tracer = trace_lib.Tracer()
    body = tracer.chrome_trace([tr])
    events = body["traceEvents"]
    assert events, body
    xs = [e for e in events if e.get("ph") == "X"]
    assert len(xs) == 1 and xs[0]["name"] == "prefill"
    # Chrome trace-event required keys; ts/dur in microseconds.
    assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(xs[0])
    assert xs[0]["args"] == {"tokens": 5}
    json.dumps(body)  # loadable JSON


def test_flight_recorder_bound_and_lookup():
    tracer = trace_lib.Tracer(capacity=3)
    traces = [tracer.start_trace("request", id=f"r{i}") for i in range(5)]
    kept = [t["id"] for t in tracer.snapshot()]
    assert kept == ["r4", "r3", "r2"]  # newest first, oldest evicted
    assert tracer.get("r0") is None
    assert tracer.get("r4") is traces[4]
    # In-flight traces are visible before finish().
    assert tracer.snapshot()[0]["done"] is False
    # capacity 0 clamps to 1 instead of crashing the first start_trace.
    t0 = trace_lib.Tracer(capacity=0)
    t0.start_trace("request", id="a")
    t0.start_trace("request", id="b")
    assert [t["id"] for t in t0.snapshot()] == ["b"]


def test_contextvar_propagation_and_noop():
    tr = trace_lib.Trace("request")
    # Outside activate(): helpers are no-ops, not errors.
    with trace_lib.span("ignored"):
        pass
    trace_lib.add_complete("ignored", trace_lib.now_ns())
    assert trace_lib.current() is None
    with trace_lib.activate(tr):
        assert trace_lib.current() is tr
        with trace_lib.span("inside"):
            pass
        trace_lib.add_complete("chunk", trace_lib.now_ns())
    assert trace_lib.current() is None
    assert [s.name for s in tr.spans] == ["inside", "chunk"]
    # Threads don't inherit another thread's active trace.
    seen = []
    t = threading.Thread(target=lambda: seen.append(trace_lib.current()))
    with trace_lib.activate(tr):
        t.start()
        t.join()
    assert seen == [None]


def test_jsonl_roundtrip_and_windows(tmp_path):
    tracer = trace_lib.Tracer()
    tr = tracer.start_trace("request", id="rid1")
    tr.add_complete("decode_chunk", 1_000, 500)
    tr.add_complete("decode_chunk", 2_000, 700)
    tr.add_complete("emission", 3_000, 10)
    tr.finish()
    path = tmp_path / "flight.jsonl"
    assert tracer.write_jsonl(str(path)) == 1
    windows = trace_lib.windows_from_jsonl(str(path))
    assert windows == [
        ("rid1:decode_chunk[0]", 1_000, 1_500),
        ("rid1:decode_chunk[1]", 2_000, 2_700),
    ]


def test_watchdog_one_dump_per_stall():
    tracer = trace_lib.Tracer()
    tr = tracer.start_trace("request", id="stuck1")
    tr.begin("decode_chunk")
    out = io.StringIO()
    wd = trace_lib.StallWatchdog(
        tracer, 0.15, name="test", out=out
    ).start()
    try:
        # Inactive: a missing beat is not a stall.
        time.sleep(0.4)
        assert wd.dumps == 0
        # Active with no beats: exactly ONE dump, however long it stalls.
        wd.set_active(True)
        deadline = time.monotonic() + 5
        while wd.dumps == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert wd.dumps == 1
        time.sleep(0.5)
        assert wd.dumps == 1  # still one: re-armed only by a beat
        text = out.getvalue()
        assert "STALL WATCHDOG" in text
        assert "stuck1" in text  # flight-recorder tail is in the dump
        assert "MainThread" in text  # thread stacks are in the dump
        # A beat re-arms; the next stall dumps again.
        wd.beat()
        deadline = time.monotonic() + 5
        while wd.dumps == 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert wd.dumps == 2
    finally:
        wd.stop()


def test_watchdog_beats_prevent_dumps():
    wd = trace_lib.StallWatchdog(None, 0.2, name="test", out=io.StringIO())
    wd.start()
    try:
        wd.set_active(True)
        for _ in range(8):
            time.sleep(0.05)
            wd.beat()
        assert wd.dumps == 0
    finally:
        wd.stop()


def test_now_ns_monotone_and_anchored():
    a = trace_lib.now_ns()
    b = trace_lib.now_ns()
    assert b >= a
    # Anchored to the wall clock (needed for the xplane join).
    assert abs(a - time.time_ns()) < 60 * 1_000_000_000


def test_span_handle_resolves_under_concurrent_appends():
    """Regression (oryxlint lock-discipline self-application): span()
    used to chase its handle into the span list OUTSIDE the lock while
    other threads append — it must yield the right span, and keep
    doing so with writers running."""
    tr = trace_lib.Trace("req")
    stop = threading.Event()

    def appender():
        while not stop.is_set():
            tr.add_complete("noise", trace_lib.now_ns(), 10)

    workers = [threading.Thread(target=appender) for _ in range(2)]
    for w in workers:
        w.start()
    try:
        for i in range(200):
            with tr.span("work", i=i) as sp:
                assert sp.name == "work"
                assert sp.args == {"i": i}
    finally:
        stop.set()
        for w in workers:
            w.join()
    tr.finish()
    names = {s.name for s in tr.spans}
    assert names == {"noise", "work"}
