"""Anomaly detectors (utils/anomaly.py): synthetic NaN / spike /
collapse streams fire exactly-one structured events (JSONL sink +
oryx_anomaly_total{kind=} counter), a steady stream fires nothing, and
the SLO detectors re-arm with hysteresis."""

import json
import math

import numpy as np
import pytest

from oryx_tpu.utils.anomaly import (
    AnomalyHalt,
    AnomalyMonitor,
    AnomalyThresholds,
)
from oryx_tpu.utils.metrics import Registry


def _events(path) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_nan_loss_stream_exactly_one_event(tmp_path):
    """Acceptance: a synthetic NaN-loss stream -> exactly one nan_loss
    event in events.jsonl plus oryx_anomaly_total{kind="nan_loss"} == 1."""
    path = tmp_path / "events.jsonl"
    reg = Registry(prefix="oryx_train")
    mon = AnomalyMonitor(source="train", events_path=str(path), registry=reg)
    for step in range(1, 21):
        loss = 2.0 if step < 5 else float("nan")
        mon.observe_train_step(step, loss)
    evs = _events(path)
    assert len(evs) == 1
    ev = evs[0]
    assert ev["kind"] == "nan_loss"
    assert ev["source"] == "train"
    assert ev["value"] is None  # NaN serializes as RFC-strict null
    assert ev["context"]["step"] == 5
    assert "time_unix_s" in ev and "message" in ev
    assert 'oryx_anomaly_total{kind="nan_loss"} 1' in reg.render()
    mon.close()


def test_nan_loss_rearms_after_recovery(tmp_path):
    path = tmp_path / "events.jsonl"
    mon = AnomalyMonitor(events_path=str(path))
    stream = [1.0, float("nan"), float("nan"), 1.0, float("inf")]
    for i, loss in enumerate(stream):
        mon.observe_train_step(i, loss)
    kinds = [e["kind"] for e in _events(path)]
    assert kinds == ["nan_loss", "nan_loss"]  # one per episode, not per step


def test_steady_stream_no_false_positives(tmp_path):
    """A noisy-but-healthy run must stay silent: loss wandering within
    2x, grad norms within 3x, throughput within 30%."""
    path = tmp_path / "events.jsonl"
    mon = AnomalyMonitor(events_path=str(path))
    rng = np.random.default_rng(0)
    for step in range(200):
        fired = mon.observe_train_step(
            step,
            loss=2.0 + 0.3 * rng.standard_normal(),
            grad_norm=1.0 + 0.2 * abs(rng.standard_normal()),
            tokens_per_sec=1000.0 * (1 + 0.15 * rng.standard_normal()),
        )
        assert fired == []
    assert not path.exists() or _events(path) == []
    assert mon.total == 0


def test_loss_spike_one_shot():
    mon = AnomalyMonitor(thresholds=AnomalyThresholds(min_window=4))
    for step in range(10):
        assert mon.observe_train_step(step, 1.0) == []
    fired = mon.observe_train_step(10, 50.0)
    assert [e.kind for e in fired] == ["loss_spike"]
    assert fired[0].value == 50.0
    assert fired[0].threshold == pytest.approx(3.0)  # 3x median 1.0
    # Still elevated: no re-fire until it drops back under the line.
    assert mon.observe_train_step(11, 49.0) == []


def test_cold_start_spike_silent():
    """min_window unmet: a wild early loss must not alert (step-1
    losses are routinely 10x the converged value)."""
    mon = AnomalyMonitor(thresholds=AnomalyThresholds(min_window=8))
    assert mon.observe_train_step(0, 1.0) == []
    assert mon.observe_train_step(1, 100.0) == []


def test_grad_norm_explosion():
    mon = AnomalyMonitor(thresholds=AnomalyThresholds(min_window=4))
    for step in range(8):
        mon.observe_train_step(step, 1.0, grad_norm=0.5)
    fired = mon.observe_train_step(8, 1.0, grad_norm=500.0)
    assert [e.kind for e in fired] == ["grad_norm_explosion"]


def test_throughput_collapse_does_not_rebaseline():
    """Collapsed samples must NOT enter the rolling window — otherwise
    the median drifts down onto the collapsed level and a permanently
    degraded run stops looking anomalous."""
    mon = AnomalyMonitor(thresholds=AnomalyThresholds(min_window=4))
    for step in range(10):
        mon.observe_train_step(step, 1.0, tokens_per_sec=1000.0)
    fired = mon.observe_train_step(10, 1.0, tokens_per_sec=10.0)
    assert [e.kind for e in fired] == ["throughput_collapse"]
    for step in range(11, 40):
        assert mon.observe_train_step(step, 1.0, tokens_per_sec=10.0) == []
    # Window median still reflects the healthy regime.
    assert mon._tput.median() == pytest.approx(1000.0)
    # Recovery re-arms; a second collapse fires a second event.
    mon.observe_train_step(40, 1.0, tokens_per_sec=900.0)
    fired = mon.observe_train_step(41, 1.0, tokens_per_sec=5.0)
    assert [e.kind for e in fired] == ["throughput_collapse"]
    assert mon.counts["throughput_collapse"] == 2


def test_ttft_slo_disabled_by_default_and_rearms():
    mon = AnomalyMonitor(source="serve")
    assert mon.observe_ttft(999.0) == []  # no SLO configured -> silent
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(ttft_slo_s=1.0),
    )
    assert [e.kind for e in mon.observe_ttft(2.0, request_id="r1")] == [
        "ttft_slo"
    ]
    assert mon.observe_ttft(3.0) == []  # still breached: one per episode
    assert mon.observe_ttft(0.5) == []  # compliant -> re-arm
    assert [e.kind for e in mon.observe_ttft(2.0)] == ["ttft_slo"]


def test_queue_depth_slo_one_rearms_on_drain():
    """slo=1 regression: the drain-side observation (depth 0) must
    re-arm the detector — with submit-only feeding it never could."""
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(queue_depth_slo=1),
    )
    assert [e.kind for e in mon.observe_queue_depth(2)] == [
        "queue_depth_slo"
    ]
    assert mon.observe_queue_depth(0) == []  # scheduler drained
    assert [e.kind for e in mon.observe_queue_depth(2)] == [
        "queue_depth_slo"
    ]


def test_window_engine_rejects_slo_flags():
    """The window batcher never feeds the SLO detectors; accepting the
    flags there would look armed while every breach went unobserved."""
    from oryx_tpu.serve import api_server

    with pytest.raises(ValueError, match="scheduler engine"):
        api_server.build_server(None, engine="window", ttft_slo=1.0)
    with pytest.raises(ValueError, match="scheduler engine"):
        api_server.build_server(None, engine="window", queue_depth_slo=4)


def test_queue_depth_hysteresis():
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(queue_depth_slo=10),
    )
    assert [e.kind for e in mon.observe_queue_depth(11)] == [
        "queue_depth_slo"
    ]
    assert mon.observe_queue_depth(12) == []
    # Dropping just under the SLO does not re-arm (oscillation guard)...
    assert mon.observe_queue_depth(9) == []
    assert mon.observe_queue_depth(11) == []
    # ...draining to half does.
    assert mon.observe_queue_depth(5) == []
    assert [e.kind for e in mon.observe_queue_depth(11)] == [
        "queue_depth_slo"
    ]


def test_event_jsonl_is_rfc_strict(tmp_path):
    """Every sink line must json.loads cleanly (jq/JSON.parse consumers)
    even when the payload is the non-finite value itself."""
    path = tmp_path / "events.jsonl"
    mon = AnomalyMonitor(events_path=str(path))
    mon.observe_train_step(1, float("inf"))
    raw = path.read_text()
    assert "Infinity" not in raw and "NaN" not in raw
    assert _events(path)[0]["value"] is None


def test_halt_policy_via_train_telemetry(tmp_path):
    """--on-anomaly=halt: the first anomaly raises AnomalyHalt out of
    record_step (and the exporter flips /readyz not-ready)."""
    from oryx_tpu.train.telemetry import TrainTelemetry

    tel = TrainTelemetry(
        port=None, events_path=str(tmp_path / "ev.jsonl"),
        on_anomaly="halt",
    )
    tel.mark_ready()
    tel.record_step(1, {"loss": 2.0, "num_tokens": 10}, step_seconds=0.1)
    with pytest.raises(AnomalyHalt) as ei:
        tel.record_step(
            2, {"loss": float("nan"), "num_tokens": 10}, step_seconds=0.1
        )
    assert ei.value.events[0].kind == "nan_loss"
    assert tel._ready is False and "halted" in tel._ready_reason
    assert len(_events(tmp_path / "ev.jsonl")) == 1
    tel.close()

    with pytest.raises(ValueError, match="on_anomaly"):
        TrainTelemetry(port=None, on_anomaly="explode")


def test_warn_policy_keeps_training(tmp_path):
    from oryx_tpu.train.telemetry import TrainTelemetry

    tel = TrainTelemetry(port=None, on_anomaly="warn")
    evs = tel.record_step(
        1, {"loss": float("nan"), "num_tokens": 10}, step_seconds=0.1
    )
    assert [e.kind for e in evs] == ["nan_loss"]
    assert math.isnan(tel.registry.get("loss"))
    assert 'oryx_anomaly_total{kind="nan_loss"} 1' in tel.registry.render()
    tel.close()


def test_events_jsonl_size_capped_rotation(tmp_path):
    """The sink must not grow without bound: past events_max_bytes the
    file rolls to events.jsonl.1 and a fresh file starts. Both files
    stay valid JSONL, the live file stays under ~cap + one event, and
    the newest event is in the live file."""
    path = tmp_path / "events.jsonl"
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(ttft_slo_s=1.0),
        events_path=str(path),
        events_max_bytes=400,
    )
    for i in range(20):
        fired = mon.observe_ttft(2.0, request_id=f"req-{i:02d}")
        assert len(fired) == 1  # re-armed below, so every breach fires
        mon.observe_ttft(0.1)  # clear -> re-arm
    mon.close()
    assert mon.counts["ttft_slo"] == 20
    rolled = tmp_path / "events.jsonl.1"
    assert rolled.exists(), "rotation never rolled to events.jsonl.1"
    live, old = _events(path), _events(rolled)
    for ev in live + old:  # every surviving line is a whole event
        assert ev["kind"] == "ttft_slo"
    # The live file was rotated down: bounded by the cap plus at most
    # the one event whose write crossed it.
    assert path.stat().st_size < 400 + 300
    assert any(
        ev["context"]["request_id"] == "req-19" for ev in live + old
    ), "the newest event was lost in rotation"
    # Rotation preserves ordering: old file's events all precede the
    # live file's.
    if live and old:
        assert old[-1]["time_unix_s"] <= live[0]["time_unix_s"]


def test_events_jsonl_rotation_disabled_with_zero_cap(tmp_path):
    path = tmp_path / "events.jsonl"
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(ttft_slo_s=1.0),
        events_path=str(path),
        events_max_bytes=0,
    )
    for _ in range(10):
        mon.observe_ttft(2.0)
        mon.observe_ttft(0.1)
    mon.close()
    assert not (tmp_path / "events.jsonl.1").exists()
    assert len(_events(path)) == 10


# ---------------------------------------------------------------------------
# Numerics & output-quality sentinels (ISSUE 14)
# ---------------------------------------------------------------------------


def test_entropy_collapse_one_shot_no_rebaseline():
    """A collapsing logits entropy fires once per episode, collapsed
    values never enter the rolling window (no silent re-baselining),
    and a recovery re-arms."""
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(min_window=4, entropy_floor_frac=0.5),
    )
    for _ in range(6):
        assert mon.observe_numerics(entropy=4.0) == []
    fired = mon.observe_numerics(entropy=0.5)
    assert [e.kind for e in fired] == ["entropy_collapse"]
    # Still collapsed: silent (episode), and the window median is
    # untouched by the collapsed samples.
    for _ in range(10):
        assert mon.observe_numerics(entropy=0.4) == []
    assert mon.counts["entropy_collapse"] == 1
    # Recovery re-arms; a second collapse is a second episode.
    for _ in range(3):
        assert mon.observe_numerics(entropy=4.0) == []
    assert [e.kind for e in mon.observe_numerics(entropy=0.3)] == [
        "entropy_collapse"
    ]
    assert mon.counts["entropy_collapse"] == 2
    mon.close()


def test_absmax_explosion_spikes_enter_window():
    """absmax mirrors grad_norm_explosion: one event per episode, and
    spikes DO enter the window (a genuinely higher plateau becomes the
    baseline instead of firing forever)."""
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(min_window=4, absmax_factor=4.0),
    )
    for _ in range(6):
        assert mon.observe_numerics(absmax=10.0) == []
    fired = mon.observe_numerics(absmax=100.0)
    assert [e.kind for e in fired] == ["absmax_explosion"]
    assert mon.observe_numerics(absmax=100.0) == []  # same episode
    # Keep feeding the new plateau: it enters the window, the median
    # climbs, and the detector stops considering it anomalous.
    for _ in range(12):
        mon.observe_numerics(absmax=100.0)
    assert mon.observe_numerics(absmax=100.0) == []
    assert mon.counts["absmax_explosion"] == 1
    mon.close()


def test_audit_drift_episode_semantics():
    mon = AnomalyMonitor(source="serve")
    assert [e.kind for e in mon.observe_audit("drift")] == ["audit_drift"]
    assert mon.observe_audit("fail") == []  # same episode
    assert mon.observe_audit("pass") == []  # re-arms
    assert [e.kind for e in mon.observe_audit("fail")] == ["audit_drift"]
    assert mon.counts["audit_drift"] == 2
    ev = mon.recent[-1]
    assert ev.context["verdict"] == "fail"
    mon.close()


def test_spec_accept_collapse_rolling_baseline():
    """Accept-rate off its own rolling baseline: one event per
    collapse episode, collapsed rates stay out of the window, recovery
    re-arms — and a drafter that was never good (baseline ~1.0) can
    never fire (1.0 is the floor of the signal)."""
    mon = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(
            min_window=4, spec_accept_floor_frac=0.5,
        ),
    )
    for _ in range(8):
        assert mon.observe_spec_accept(4.0) == []
    fired = mon.observe_spec_accept(1.0)
    assert [e.kind for e in fired] == ["spec_accept_collapse"]
    for _ in range(5):
        assert mon.observe_spec_accept(1.0) == []
    assert mon.counts["spec_accept_collapse"] == 1
    for _ in range(3):
        assert mon.observe_spec_accept(4.0) == []
    assert [e.kind for e in mon.observe_spec_accept(1.5)] == [
        "spec_accept_collapse"
    ]
    mon.close()
    # Never-good drafter: baseline 1.0, rate can't go below 0.5x it.
    mon2 = AnomalyMonitor(source="serve")
    for _ in range(40):
        assert mon2.observe_spec_accept(1.0) == []
    assert mon2.counts.get("spec_accept_collapse", 0) == 0
    mon2.close()


def test_window_engine_rejects_audit_and_numerics_flags():
    """--audit-sample-every/--numerics-every on the window batcher must
    fail fast (no paged replay path / engine step loop), same contract
    as the SLO flags."""
    from oryx_tpu.serve import api_server

    with pytest.raises(ValueError, match="audit-sample-every"):
        api_server.build_server(
            object(), engine="window", audit_sample_every=1, port=0,
        )
    with pytest.raises(ValueError, match="numerics-every"):
        api_server.build_server(
            object(), engine="window", numerics_every=4, port=0,
        )
