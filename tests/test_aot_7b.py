"""Oryx-7B on a v5e-16: AOT per-chip memory proof (SURVEY.md §7 hard
part 5; VERDICT r4 "prove the 7B-on-a-mesh memory math end-to-end in
AOT").

Drives scripts/estimate_7b_mesh_memory.py, which compiles the FULL
sharded train step for the shipped `scripts/configs/oryx_7b_sft.json`
with the REAL XLA:TPU compiler against a v5e:4x4 (16-chip) topology —
local libtpu, no chips attached — and pins:

  * ZeRO-3 sharding: per-chip argument bytes == total state / 16 (a
    replicated embedding or moment tree would blow the 5% tolerance);
  * the production point (remat=attn, fp32 moments, grad_accum=8, i.e.
    1 row/chip/microbatch) FITS the 16 GB HBM;
  * the whole-step accum=1 compile does NOT fit — the pinned record of
    why the shipped config carries grad_accum_steps=8.

The script re-execs itself into a clean CPU-client child; the TPU
*compiler* target comes from the topology API, so this runs anywhere
libtpu is installed. Numbers recorded in TPU_VALIDATION.md (round 5).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "estimate_7b_mesh_memory.py")


def _have_tpu_compiler() -> bool:
    import importlib.util

    return importlib.util.find_spec("libtpu") is not None


@pytest.mark.slow
def test_7b_v5e16_aot_memory():
    if not _have_tpu_compiler():
        pytest.skip("libtpu not installed (TPU topology AOT unavailable)")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "attn:float32:8", "attn:float32:1"],
        capture_output=True, text=True, timeout=3000,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [
        json.loads(l) for l in proc.stdout.splitlines()
        if l.startswith("{")
    ]
    recs = {(r["policy"], r["grad_accum_steps"]): r
            for r in lines if "policy" in r}
    summary = next(l for l in lines if "winner" in l)

    prod = recs[("attn", 8)]
    assert prod["target"] == "tpu_v5e_4x4_topology"
    # ZeRO-3: every large leaf actually sharded 16 ways.
    assert prod["sharded_ok"], prod
    # ~90 GB fp32 state over 16 chips ≈ 5.6 GB/chip of arguments.
    assert 5.0 < prod["args_gb"] < 6.5, prod
    # The production point fits v5e HBM (measured 15.01 GB total at
    # pinning time; keep a little slack for compiler drift).
    assert prod["fits_16gb"], prod
    assert prod["total_gb"] < 16.0, prod

    # Whole-step (accum=1) does NOT fit: 8 rows/chip of activations
    # blow the budget — the reason the shipped config accumulates. The
    # TPU compiler enforces HBM at compile time, so this surfaces as a
    # captured RESOURCE_EXHAUSTED with the required footprint (measured
    # 16.00 GB vs 15.75 usable for the shipped Pallas program; 17.27 on
    # the xla path).
    whole = recs[("attn", 1)]
    assert not whole["fits_16gb"], whole
    assert whole.get("oom"), whole
    if whole.get("total_gb"):
        assert whole["total_gb"] > 15.75, whole

    assert summary["winner"] == "attn:float32:8", summary
