"""Metrics registry (utils/metrics.py): exposition well-formedness,
label escaping, histogram bucket math, concurrency, duplicate-family
rejection, collectors, and the TelemetryServer HTTP surface — the
backbone both ServingMetrics and the trainer exporter sit on."""

import json
import math
import re
import threading
import urllib.error
import urllib.request

import pytest

from oryx_tpu.utils.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    Registry,
    ServingMetrics,
    TelemetryServer,
    register_device_memory_collector,
    register_process_collector,
)

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][\w:]*)(\{[^}]*\})? (-?[\d.e+-]+|[+-]?inf|nan)$"
)


def parse_exposition(text: str) -> dict[str, float]:
    """Assert Prometheus text well-formedness; return sample map with
    labels folded into the key."""
    values = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(r"^# TYPE (\S+) (counter|gauge|histogram)$", line)
            assert m, line
            assert m.group(1) not in types, f"duplicate family {line!r}"
            types[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        values[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return values


def test_counter_gauge_prefix_and_get():
    r = Registry(prefix="oryx_test")
    r.counter("reqs").inc()
    r.counter("reqs").inc(2.5)
    r.gauge("depth").set(7)
    assert r.get("reqs") == 3.5
    assert r.get("depth") == 7
    assert r.get("never_touched") == 0.0
    v = parse_exposition(r.render())
    assert v["oryx_test_reqs"] == 3.5
    assert v["oryx_test_depth"] == 7


def test_raw_name_skips_prefix():
    r = Registry(prefix="oryx_train")
    r.counter("oryx_anomaly_total", ("kind",), raw_name=True).labels(
        kind="nan_loss"
    ).inc()
    v = parse_exposition(r.render())
    assert v['oryx_anomaly_total{kind="nan_loss"}'] == 1


def test_negative_counter_increment_rejected():
    r = Registry()
    with pytest.raises(ValueError, match=">= 0"):
        r.counter("c").inc(-1)


def test_label_escaping():
    r = Registry(prefix="p")
    r.gauge("g", ("path",)).labels(path='a\\b"c\nd').set(1)
    text = r.render()
    assert 'path="a\\\\b\\"c\\nd"' in text
    # Escaped value stays on ONE line (the newline must not split it).
    assert len([l for l in text.splitlines() if l.startswith("p_g{")]) == 1


def test_label_names_must_match_declaration():
    r = Registry()
    fam = r.counter("c", ("kind",))
    with pytest.raises(ValueError, match="declares"):
        fam.labels(other="x")


def test_histogram_bucket_math():
    r = Registry(prefix="h")
    hist = r.histogram("lat", (0.1, 1.0, 10.0))
    for x in (0.05, 0.5, 0.5, 5.0, 50.0):
        hist.observe(x)
    v = parse_exposition(r.render())
    # Cumulative le-buckets; +Inf == total count; exact sum.
    assert v['h_lat_bucket{le="0.1"}'] == 1
    assert v['h_lat_bucket{le="1"}'] == 3
    assert v['h_lat_bucket{le="10"}'] == 4
    assert v['h_lat_bucket{le="+Inf"}'] == 5
    assert v["h_lat_count"] == 5
    assert v["h_lat_sum"] == pytest.approx(56.05)


def test_histogram_with_labels_renders_per_child():
    r = Registry()
    fam = r.histogram("lat", (1.0,), ("engine",))
    fam.labels(engine="a").observe(0.5)
    fam.labels(engine="b").observe(2.0)
    v = parse_exposition(r.render())
    assert v['lat_bucket{engine="a",le="1"}'] == 1
    assert v['lat_bucket{engine="b",le="1"}'] == 0
    assert v['lat_count{engine="a"}'] == 1
    assert v['lat_count{engine="b"}'] == 1


def test_duplicate_family_rejected():
    # The kind clash below is the POINT of the test (the runtime twin
    # of oryxlint's metric-name rule) — hence the suppressions.
    r = Registry()
    r.counter("x")  # oryxlint: disable=metric-name
    with pytest.raises(ValueError, match="re-declared"):
        r.gauge("x")  # oryxlint: disable=metric-name
    with pytest.raises(ValueError, match="re-declared"):
        r.counter("x", ("kind",))  # oryxlint: disable=metric-name
    # Identical re-declaration returns the same family.
    assert r.counter("x") is r.counter("x")  # oryxlint: disable=metric-name


def test_concurrent_increments_exact():
    r = Registry()
    c = r.counter("hits")
    fam = r.counter("by_kind", ("kind",))
    h = r.histogram("obs", (0.5,))
    N, T = 500, 8

    def work(i):
        for _ in range(N):
            c.inc()
            fam.labels(kind=f"k{i % 2}").inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    v = parse_exposition(r.render())
    assert v["hits"] == N * T
    assert v['by_kind{kind="k0"}'] + v['by_kind{kind="k1"}'] == N * T
    assert v["obs_count"] == N * T
    assert v['obs_bucket{le="0.5"}'] == N * T


def test_info_metric_replaces():
    r = Registry(prefix="s")
    r.info("build_info", {"revision": "abc", "engine": "window"})
    r.info("build_info", {"revision": "def", "engine": "continuous"})
    v = parse_exposition(r.render())
    assert v == {
        's_build_info{engine="continuous",revision="def"}': 1.0
    }
    # info() may replace only INFO families — clobbering a live
    # counter would violate the no-duplicate-family invariant. (The
    # deliberate kind clash is what's under test here.)
    r.counter("live_counter").inc()  # oryxlint: disable=metric-name
    with pytest.raises(ValueError, match="already registered"):
        r.info("live_counter", {"k": "v"})  # oryxlint: disable=metric-name
    assert r.get("live_counter") == 1


def test_get_on_histogram_and_labeled_is_zero():
    r = Registry()
    r.histogram("lat", (1.0,)).observe(0.5)
    r.counter("by_kind", ("kind",)).labels(kind="a").inc()
    assert r.get("lat") == 0.0  # no single scalar: convenience zero
    assert r.get("by_kind") == 0.0
    m = ServingMetrics()
    assert m.get("ttft_seconds") == 0.0  # pre-created histogram


def test_collectors_refresh_on_render_and_never_break_scrape():
    r = Registry()
    g = r.gauge("fresh")
    state = {"n": 0}

    def collect():
        state["n"] += 1
        g.set(state["n"])

    def broken():
        raise RuntimeError("boom")

    r.register_collector(collect)
    r.register_collector(broken)
    parse_exposition(r.render())
    v = parse_exposition(r.render())
    assert v["fresh"] == 2  # refreshed per render; broken one swallowed


def test_process_and_device_memory_collectors():
    r = Registry(prefix="t")
    register_process_collector(r)
    register_device_memory_collector(r)
    v = parse_exposition(r.render())
    assert v["t_process_cpu_seconds_total"] > 0
    assert v["t_process_resident_memory_bytes"] > 0
    assert v["t_process_threads"] >= 1
    assert "t_hbm_live_bytes" in v
    # Forced-host CPU backend: live_arrays is real, allocator stats 0.
    assert v["t_hbm_live_bytes"] >= 0


def test_device_memory_collector_rate_limited(monkeypatch):
    """`jax.live_arrays()` walks every live array, so the HBM
    collector caches for ~1s (monotonic): an aggressive scraper pays
    the walk at most once per TTL window, and ttl_s=0 disables the
    cache. Counting fake pins the contract."""
    import jax as jax_lib

    calls = {"n": 0}

    def counting_live_arrays():
        calls["n"] += 1
        return []

    monkeypatch.setattr(jax_lib, "live_arrays", counting_live_arrays)
    r = Registry(prefix="t")
    register_device_memory_collector(r, ttl_s=1000.0)
    for _ in range(5):
        r.render()
    assert calls["n"] == 1, calls  # cached inside the TTL window
    # Monotonic-clock based: past the TTL the walk refreshes.
    import time as time_lib

    r2 = Registry(prefix="t2")
    register_device_memory_collector(r2, ttl_s=0.05)
    calls["n"] = 0
    r2.render()
    r2.render()
    assert calls["n"] == 1, calls
    time_lib.sleep(0.06)
    r2.render()
    assert calls["n"] == 2, calls
    # ttl_s=0 disables the cache entirely.
    r3 = Registry(prefix="t3")
    register_device_memory_collector(r3, ttl_s=0)
    calls["n"] = 0
    for _ in range(3):
        r3.render()
    assert calls["n"] == 3, calls


def test_serving_metrics_compat_surface():
    """ServingMetrics is now a Registry client; the old call surface
    (inc/set_gauge/observe/get/render, creation-only buckets) must be
    byte-compatible for the scheduler and the endpoint gates."""
    m = ServingMetrics()
    m.inc("admitted")
    m.set_gauge("queue_depth", 2)
    m.observe("ttft_seconds", 0.3)
    m.observe("ttft_seconds", 0.3, buckets=(99.0,))  # ignored: exists
    m.set_info("build_info", {"revision": "r", "engine": "e", "model": "m"})
    assert m.get("admitted") == 1
    assert m.get("queue_depth") == 2
    text = m.render()
    v = parse_exposition(text)
    assert v["oryx_serving_admitted"] == 1
    # Both observations recorded into the ORIGINAL ladder (the second
    # call's bucket arg was ignored, not a new family).
    assert v['oryx_serving_ttft_seconds_bucket{le="0.5"}'] == 2
    assert v['oryx_serving_ttft_seconds_bucket{le="+Inf"}'] == 2
    # Pre-created ladders render from first touch.
    assert "oryx_serving_time_per_output_token_seconds_count" in v
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert line.startswith(("oryx_serving_", "oryx_anomaly_")), line


def test_telemetry_server_endpoints():
    r = Registry(prefix="oryx_train")
    r.gauge("loss").set(1.25)
    ready = {"ok": False}
    srv = TelemetryServer(
        r, port=0,
        ready_check=lambda: (ready["ok"], "ok" if ready["ok"] else "warming"),
    ).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE
            v = parse_exposition(resp.read().decode())
        assert v["oryx_train_loss"] == 1.25
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            assert json.load(resp) == {"status": "ok"}
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/readyz", timeout=10)
        assert ei.value.code == 503
        assert json.load(ei.value) == {"ready": False, "reason": "warming"}
        ready["ok"] = True
        with urllib.request.urlopen(base + "/readyz", timeout=10) as resp:
            assert json.load(resp)["ready"] is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Shared quantile helpers (histogram bucket interpolation — the one
# implementation loadgen and check_serving_endpoints both use)
# ---------------------------------------------------------------------------


def test_histogram_quantile_interpolation():
    from oryx_tpu.utils.metrics import histogram_quantile

    # Observations 0.5, 1.5, 1.5, 3.0 over bounds (1, 2, 4):
    # cumulative counts (1, 3, 4), total 4.
    bounds, counts, total = [1.0, 2.0, 4.0], [1, 3, 4], 4
    # p50: rank 2 inside (1, 2] between cum 1 and 3 -> 1.5 exactly.
    assert histogram_quantile(0.5, bounds, counts, total) == pytest.approx(1.5)
    # p100 lands at the top of the last bucket.
    assert histogram_quantile(1.0, bounds, counts, total) == pytest.approx(4.0)
    # p25: rank 1 is the full first bucket -> its upper bound.
    assert histogram_quantile(0.25, bounds, counts, total) == pytest.approx(1.0)
    # q=0 clamps to the lower edge of the first occupied bucket.
    assert histogram_quantile(0.0, bounds, counts, total) == pytest.approx(0.0)


def test_histogram_quantile_edges():
    from oryx_tpu.utils.metrics import histogram_quantile

    # Empty histogram -> NaN.
    assert math.isnan(histogram_quantile(0.5, [1.0], [0], 0))
    assert math.isnan(histogram_quantile(0.5, [], [], 0))
    # Observations past the last finite bound clamp to it (the
    # Prometheus convention): 3 of 4 obs overflowed the ladder.
    assert histogram_quantile(0.99, [1.0], [1], 4) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        histogram_quantile(1.5, [1.0], [1], 1)


def test_parse_prom_histogram_roundtrip():
    """Render a real registry histogram, parse it back with the shared
    parser, and check the quantile is consistent with the samples."""
    from oryx_tpu.utils.metrics import (
        histogram_quantile,
        parse_prom_histogram,
    )

    reg = Registry(prefix="oryx_test")
    h = reg.histogram("lat_seconds", (0.1, 0.5, 1.0, 5.0))
    for v in (0.05, 0.2, 0.3, 0.7, 2.0, 9.0):
        h.observe(v)
    text = reg.render()
    parsed = parse_prom_histogram(text, "oryx_test_lat_seconds")
    assert parsed is not None
    bounds, counts, total, s = parsed
    assert bounds == [0.1, 0.5, 1.0, 5.0]
    assert counts == [1, 3, 4, 5]
    assert total == 6
    assert s == pytest.approx(12.25)
    p50 = histogram_quantile(0.5, bounds, counts, total)
    assert 0.1 <= p50 <= 0.5  # the median sample (0.3-ish bucket)
    # Absent family -> None, never a crash.
    assert parse_prom_histogram(text, "oryx_test_nope_seconds") is None


def test_sample_quantile_exact():
    from oryx_tpu.utils.metrics import sample_quantile

    assert math.isnan(sample_quantile([], 0.5))
    assert sample_quantile([3.0], 0.99) == 3.0
    vals = [4.0, 1.0, 3.0, 2.0]
    assert sample_quantile(vals, 0.5) == pytest.approx(2.5)
    assert sample_quantile(vals, 0.0) == 1.0
    assert sample_quantile(vals, 1.0) == 4.0
    with pytest.raises(ValueError):
        sample_quantile(vals, -0.1)


# ---------------------------------------------------------------------------
# Concurrent scrapes under write load (registry thread-safety + no
# torn exposition lines)
# ---------------------------------------------------------------------------


def _assert_histograms_consistent(text: str) -> None:
    """Within ONE exposition, every histogram's bucket counts must be
    cumulative non-decreasing and its +Inf bucket must equal its
    _count line — a torn render (counts snapshotted mid-observe)
    breaks one of these."""
    import collections

    buckets: dict[str, list[tuple[float, int]]] = collections.defaultdict(list)
    counts: dict[str, int] = {}
    for line in text.splitlines():
        m = re.match(r'^(\S+)_bucket\{le="([^"]+)"\} (\d+)$', line)
        if m:
            le = float("inf") if m.group(2) == "+Inf" else float(m.group(2))
            buckets[m.group(1)].append((le, int(m.group(3))))
            continue
        m = re.match(r"^(\S+)_count (\d+)$", line)
        if m:
            counts[m.group(1)] = int(m.group(2))
    assert buckets, "no histograms in exposition"
    for name, bs in buckets.items():
        cs = [c for _, c in sorted(bs)]
        assert cs == sorted(cs), f"{name}: non-cumulative buckets {bs}"
        assert cs[-1] == counts[name], (
            f"{name}: +Inf bucket {cs[-1]} != count {counts[name]}"
        )


def test_concurrent_scrapes_no_torn_lines():
    """Writers hammering counters/gauges/histograms (labeled children
    included) while readers render: every exposition parses line-clean
    (parse_exposition asserts per-line well-formedness and no
    duplicate TYPE), and every histogram is internally consistent."""
    import random as random_lib

    reg = Registry(prefix="oryx_test")
    c = reg.counter("ops_total")
    g = reg.gauge("depth")
    h = reg.histogram("lat_seconds", (0.1, 0.5, 1.0, 5.0))
    lbl = reg.counter("kinds_total", ("kind",))
    stop = threading.Event()
    failures: list[BaseException] = []

    def writer(seed: int) -> None:
        rng = random_lib.Random(seed)
        while not stop.is_set():
            c.inc()
            g.set(rng.random() * 100)
            h.observe(rng.random() * 10)
            lbl.labels(kind=f"k{rng.randrange(4)}").inc()

    def reader() -> None:
        try:
            for _ in range(40):
                text = reg.render()
                parse_exposition(text)
                _assert_histograms_consistent(text)
        except BaseException as e:  # surfaces through `failures`
            failures.append(e)

    writers = [
        threading.Thread(target=writer, args=(i,), daemon=True)
        for i in range(4)
    ]
    readers = [threading.Thread(target=reader) for _ in range(4)]
    for t in writers + readers:
        t.start()
    for t in readers:
        t.join(timeout=120)
    stop.set()
    for t in writers:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in readers), "reader hung"
    assert not failures, failures[0]
