"""Worker process for tests/test_multiprocess.py (ring-attention leg) —
NOT a pytest module.

sp=8 over 8 devices split across two processes: the decoder's ring
attention rotates K/V blocks with lax.ppermute around a ring that
CROSSES the process boundary twice per revolution — the single-box
analog of ring attention over ICI/DCN on a multi-host pod. Reuses the
driver-facing dryrun harness (__graft_entry__._dryrun_one_mesh) so the
exact program the driver compile-checks is what runs multi-process.

Run directly (in 2 processes):
    python tests/mp_ring_worker.py <pid> <port>
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
))
from mp_common import bootstrap  # noqa: E402

pid, jax = bootstrap()

import __graft_entry__ as graft  # noqa: E402

graft._dryrun_one_mesh(8, 1, 1, 1, 8)  # prints "dryrun_multichip ok: ..."
print(json.dumps({
    "mp_result": True, "pid": pid,
    "process_count": jax.process_count(),
}), flush=True)
