"""OOM forensics (utils/forensics + the scheduler's capture sites):
ring bounds and indexing, the oom_pressure wide-event schema, exactly
one record per injected OutOfPagesError with a non-empty top-K, and a
degraded-mode escalation capturing the same artifact."""

import pytest

import jax

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import oryx
from oryx_tpu.serve.pipeline import OryxInference
from oryx_tpu.serve.scheduler import ContinuousScheduler
from oryx_tpu.utils import faults
from oryx_tpu.utils.forensics import ForensicRing
from oryx_tpu.utils.metrics import OOM_EVENT_KEYS, ServingMetrics
from oryx_tpu.utils.request_log import RequestLog, build_oom_event


class FakeTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


@pytest.fixture(scope="module")
def pipe():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    return OryxInference(FakeTokenizer(), params, cfg)


# ---------------------------------------------------------------------------
# Ring semantics
# ---------------------------------------------------------------------------


def test_ring_bounds_and_monotone_index():
    ring = ForensicRing(keep=3)
    idxs = [ring.append({"kind": "oom_pressure", "n": i})
            for i in range(5)]
    assert idxs == [0, 1, 2, 3, 4]
    assert ring.total == 5
    recs = ring.snapshot()
    assert [r["n"] for r in recs] == [4, 3, 2]  # newest first, bounded
    assert ring.snapshot(1)[0]["n"] == 4
    body = ring.to_dict(2)
    assert body["total"] == 5 and len(body["records"]) == 2
    # Snapshots are copies — mutating one never corrupts the ring.
    recs[0]["n"] = 99
    assert ring.snapshot(1)[0]["n"] == 4


def test_oom_event_schema_enforced():
    ev = build_oom_event(trigger="oom", detail="x", free_pages=3)
    assert ev["kind"] == "oom_pressure" and ev["schema"] == 1
    assert set(ev) <= set(OOM_EVENT_KEYS)
    with pytest.raises(ValueError, match="OOM_EVENT_KEYS"):
        # Splat-spelled so oryxlint's static schema check (which now
        # covers build_oom_event call sites too) defers to exactly the
        # runtime validation this line exists to prove.
        build_oom_event(**{"trigger": "oom", "bogus_field": 1})
    log = RequestLog()
    log.append(ev)  # kind dispatches to the OOM schema
    with pytest.raises(ValueError):
        # A hand-rolled oom event with an undeclared key fails at the
        # sink too.
        log.append({"kind": "oom_pressure", "bogus": 1})
    with pytest.raises(ValueError):
        # An unknown kind falls back to the request schema, which has
        # no "kind" — rejected rather than silently accepted.
        log.append({"kind": "mystery_event"})


# ---------------------------------------------------------------------------
# Scheduler capture sites
# ---------------------------------------------------------------------------


def test_injected_oom_captures_one_record_with_topk(pipe):
    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        metrics=metrics, autostart=False,
    )
    faults.configure("page_alloc_oom:every=2,times=1")
    try:
        handles = [
            sched.submit(
                {"question": f"some longer burst question {i}"}, 24
            )
            for i in range(2)
        ]
        sched.start()
        results = [h.result(timeout=600) for h in handles]
    finally:
        faults.reset()
    assert all(r[0] for r in results)
    assert sched.forensics.total == 1
    rec = sched.forensics.snapshot()[0]
    assert rec["trigger"] == "oom"
    assert "OutOfPagesError" in rec["detail"] or "COW" in rec["detail"]
    assert rec["top_requests"], "empty top-K"
    top = rec["top_requests"][0]
    assert top["request_id"] and "cost" in top
    assert rec["pool"]["reconciled"]
    assert isinstance(rec["timeline_tail"], list)
    assert metrics.get("oom_forensics_total") == 0  # labeled family
    fam = metrics.registry.existing("oom_forensics_total")
    assert fam.labels(trigger="oom").value == 1
    # The flat wide event rode the request-log sink, joined by index.
    ooms = [
        e for e in sched.request_log.snapshot()
        if e.get("kind") == "oom_pressure"
    ]
    assert len(ooms) == 1
    assert ooms[0]["forensic_index"] == rec["index"]
    assert set(ooms[0]) <= set(OOM_EVENT_KEYS)
    assert ooms[0]["top_request_pages"] >= 1
    sched.close()


def test_real_shortfall_captures_once_per_episode(pipe):
    """The REAL capacity path (free list short, no exception) must
    capture a pool_pressure forensic — and exactly one per pressure
    EPISODE, not one per engine step, even though the waiting head
    retries the grow every step."""
    import math

    qs = ["pressure question A", "pressure question B"]
    cap = 48
    ps, chunk = 16, 4
    need = max(
        math.ceil(
            (len(pipe._prepare_request({"question": q})[0]) + cap
             + chunk) / ps
        )
        for q in qs
    )
    # One request fits with room to grow; two concurrent cannot —
    # the second's growth hits the free-list shortfall path.
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=chunk, max_ctx=512,
        num_pages=need + 2, prefix_cache=False, autostart=False,
    )
    handles = [sched.submit({"question": q}, cap) for q in qs]
    sched.start()
    for h in handles:
        h.result(timeout=600)
    sched.close()
    recs = sched.forensics.snapshot()
    pressure = [r for r in recs if r["trigger"] == "pool_pressure"]
    assert pressure, "shortfall left no forensic record"
    # Bounded by episodes (each successful grow closes one), never by
    # engine steps — the waiting head alone runs dozens of steps.
    assert len(recs) <= 2 * sched.metrics.get("evicted") + 4, (
        len(recs), sched.metrics.get("evicted"),
    )
    for r in pressure:
        assert r["top_requests"], r
        assert "shortfall" in r["detail"]


def test_degraded_escalation_captures_forensic(pipe):
    from oryx_tpu.utils.anomaly import AnomalyMonitor, AnomalyThresholds

    anomaly = AnomalyMonitor(
        source="serve",
        thresholds=AnomalyThresholds(queue_depth_slo=1),
    )
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        anomaly=anomaly, autostart=False,
    )
    handles = [
        sched.submit({"question": f"question {i}"}, 4)
        for i in range(4)
    ]
    sched.start()
    for h in handles:
        h.result(timeout=600)
    assert sched.forensics.total >= 1
    rec = sched.forensics.snapshot()[-1]  # oldest = the escalation
    assert rec["trigger"] == "degraded_escalation"
    assert rec["degraded_mode"] >= 1
    assert rec["pool"]["reconciled"]
    sched.close()
