"""oryxlint: fixture-driven checker tests, suppression semantics, the
CLI contract, and the repo-wide self-lint gate.

Fixture protocol (tests/lint_fixtures/): `*_pos.py` files mark every
expected finding line with `# expect: <rule>` and the test asserts the
finding set matches EXACTLY (no false positives on the rest of the
file); `*_suppressed.py` must produce zero findings but a nonzero
suppressed count; `*_clean.py` must produce zero findings and zero
suppressions.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from oryx_tpu.analysis import make_checkers, run_lint
from oryx_tpu.analysis.runner import default_files

ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"
_EXPECT = re.compile(r"#\s*expect:\s*([a-z][a-z0-9\-]*)")


def lint_sources(*sources: tuple[str, str], rules: str | None = None):
    res = run_lint(list(sources), make_checkers(rules))
    assert not res.errors, res.errors
    return res


def lint_file(path: Path, rules: str | None = None):
    return lint_sources((str(path), path.read_text()), rules=rules)


def expected_findings(path: Path) -> set[tuple[int, str]]:
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        for m in _EXPECT.finditer(line):
            out.add((i, m.group(1)))
    return out


# ---------------------------------------------------------------------------
# Fixtures: positive / suppressed / clean, per rule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "name", sorted(p.name for p in FIXTURES.glob("*_pos.py"))
)
def test_positive_fixture_exact_findings(name):
    path = FIXTURES / name
    want = expected_findings(path)
    assert want, f"{name} has no # expect: markers"
    res = lint_file(path)
    got = {(f.line, f.rule) for f in res.findings}
    assert got == want, (
        f"{name}: findings != expectations\n  extra: {sorted(got - want)}"
        f"\n  missing: {sorted(want - got)}\n  all:\n    "
        + "\n    ".join(f.format() for f in res.findings)
    )


@pytest.mark.parametrize(
    "name", sorted(p.name for p in FIXTURES.glob("*_suppressed.py"))
)
def test_suppressed_fixture_is_quiet_but_counted(name):
    res = lint_file(FIXTURES / name)
    assert not res.findings, "\n".join(f.format() for f in res.findings)
    assert res.suppressed > 0, (
        f"{name} should demonstrate at least one suppression"
    )


@pytest.mark.parametrize(
    "name", sorted(p.name for p in FIXTURES.glob("*_clean.py"))
)
def test_clean_fixture_has_nothing(name):
    res = lint_file(FIXTURES / name)
    assert not res.findings, "\n".join(f.format() for f in res.findings)
    assert res.suppressed == 0


def test_every_rule_has_fixture_coverage():
    rules_with_pos = {
        rule
        for p in FIXTURES.glob("*_pos.py")
        for _, rule in expected_findings(p)
    }
    all_rules = {c.name for c in make_checkers()}
    assert rules_with_pos == all_rules, (
        f"rules without a positive fixture: {all_rules - rules_with_pos}"
    )


# ---------------------------------------------------------------------------
# Cross-module behavior (the reason for the two-pass design)
# ---------------------------------------------------------------------------


def test_donation_registry_spans_modules():
    defs = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('kv',))\n"
        "def consume(params, kv):\n"
        "    return kv\n"
    )
    caller = (
        "from defs import consume\n"
        "def use(params, kv):\n"
        "    out = consume(params, kv)\n"
        "    return kv\n"
    )
    res = lint_sources(
        ("defs.py", defs), ("caller.py", caller),
        rules="use-after-donate",
    )
    assert [(f.path, f.line) for f in res.findings] == [("caller.py", 4)]


def test_metric_kind_conflict_across_modules():
    a = "def f(reg):\n    reg.counter('split_brain_x')\n"
    b = "def g(metrics):\n    metrics.set_gauge('split_brain_x', 1)\n"
    res = lint_sources(("a.py", a), ("b.py", b), rules="metric-name")
    assert {f.path for f in res.findings} == {"a.py", "b.py"}
    assert all("one family, one kind" in f.message for f in res.findings)


def test_jit_assignment_form_static_operand():
    src = (
        "import jax\n"
        "def fn(x, mode):\n"
        "    return x\n"
        "step = jax.jit(fn, static_argnums=(1,))\n"
        "def caller(x):\n"
        "    return step(x, ['a'])\n"
    )
    res = lint_sources(("m.py", src), rules="recompile-hazard")
    assert [f.line for f in res.findings] == [6]
    assert "list literal" in res.findings[0].message


# ---------------------------------------------------------------------------
# Suppression semantics
# ---------------------------------------------------------------------------


def test_file_level_disable():
    src = (
        "# oryxlint: disable-file=metric-name\n"
        "def f(reg):\n"
        "    reg.counter('BadName')\n"
    )
    res = lint_sources(("m.py", src))
    assert not res.findings
    assert res.suppressed == 1


def test_region_off_on():
    src = (
        "import numpy as np\n"
        "# hot-path\n"
        "def f(a, b):\n"
        "    # oryxlint: off=host-sync\n"
        "    x = np.asarray(a)\n"
        "    # oryxlint: on=host-sync\n"
        "    y = np.asarray(b)\n"
        "    return x, y\n"
    )
    res = lint_sources(("m.py", src), rules="host-sync")
    assert [f.line for f in res.findings] == [7]
    assert res.suppressed == 1


def test_unrelated_rule_suppression_does_not_mask():
    src = (
        "def f(reg):\n"
        "    reg.counter('BadName')  # oryxlint: disable=host-sync\n"
    )
    res = lint_sources(("m.py", src), rules="metric-name")
    assert [f.rule for f in res.findings] == ["metric-name"]


def test_parse_error_reported_not_crash():
    res = run_lint([("broken.py", "def f(:\n")], make_checkers())
    assert res.errors and res.errors[0][0] == "broken.py"
    assert not res.findings


# ---------------------------------------------------------------------------
# CLI contract (subprocess: stubs oryx_tpu, never imports jax)
# ---------------------------------------------------------------------------


def _cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "run_oryxlint.py"),
         *args],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
    )


def test_cli_strict_fails_on_each_positive_fixture():
    for path in sorted(FIXTURES.glob("*_pos.py")):
        out = _cli("--strict", str(path))
        assert out.returncode == 1, (path, out.stdout, out.stderr)
        rules = {rule for _, rule in expected_findings(path)}
        for rule in rules:
            assert f"[{rule}]" in out.stdout, (path, rule, out.stdout)


def test_cli_clean_fixture_exits_zero_and_json_shape():
    path = FIXTURES / "donate_clean.py"
    out = _cli("--strict", "--json", str(path))
    assert out.returncode == 0, (out.stdout, out.stderr)
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert payload["files"] == 1


def test_lockorder_interprocedural_across_modules():
    """The may-acquire-while-holding graph must cross module AND call
    boundaries: holding s._cond while calling a method (of a typed
    attribute, defined in another file) that acquires t._lock is an
    inversion when the manifest says t._lock < s._cond."""
    defs = (
        "from oryx_tpu.analysis.sanitizers import named_lock\n"
        "class Trace:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('t._lock')\n"
        "    def finish(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    caller = (
        "# lock-order: t._lock < s._cond\n"
        "from oryx_tpu.analysis.sanitizers import named_lock\n"
        "from defs import Trace\n"
        "class Sched:\n"
        "    def __init__(self):\n"
        "        self._cond = named_lock('s._cond', kind='condition')\n"
        "        self.trace = Trace()\n"
        "    def run(self):\n"
        "        with self._cond:\n"
        "            self.trace.finish()\n"
    )
    res = lint_sources(
        ("defs.py", defs), ("caller.py", caller), rules="lock-order"
    )
    assert len(res.findings) == 1, [f.format() for f in res.findings]
    f = res.findings[0]
    assert f.path == "caller.py" and "inverts" in f.message
    assert "t._lock" in f.message and "finish" in f.message
    # Reordering the manifest legalizes the same nesting.
    fixed = caller.replace(
        "# lock-order: t._lock < s._cond",
        "# lock-order: s._cond < t._lock",
    )
    res = lint_sources(
        ("defs.py", defs), ("caller.py", fixed), rules="lock-order"
    )
    assert not res.findings, [f.format() for f in res.findings]


def test_cli_list_rules_names_all_rules():
    out = _cli("--list-rules")
    assert out.returncode == 0
    for rule in ("lock-discipline", "lock-order", "atomicity",
                 "use-after-donate", "host-sync", "recompile-hazard",
                 "metric-name", "swallowed-exception", "key-linearity",
                 "terminal-path", "replay-taint"):
        assert rule in out.stdout


def test_cli_max_suppressions_ratchet(tmp_path):
    """`--max-suppressions N` is the CI ratchet: a file whose
    suppression count exceeds N exits 1 even with zero findings."""
    path = FIXTURES / "atomicity_suppressed.py"
    ok = _cli(str(path), "--max-suppressions", "5")
    assert ok.returncode == 0, (ok.stdout, ok.stderr)
    over = _cli(str(path), "--max-suppressions", "0")
    assert over.returncode == 1
    assert "exceed the --max-suppressions ratchet" in (
        over.stdout + over.stderr
    )


def test_cli_json_per_rule_breakdown():
    """The JSON artifact carries a per-rule finding/suppression
    breakdown so the CI ratchet can pin individual rules."""
    out = _cli("--json", str(FIXTURES / "keylin_pos.py"),
               str(FIXTURES / "keylin_suppressed.py"))
    assert out.returncode == 1  # the pos fixture's findings
    payload = json.loads(out.stdout)
    br = payload["by_rule"]["key-linearity"]
    assert br["findings"] == len(
        expected_findings(FIXTURES / "keylin_pos.py")
    )
    assert br["suppressed"] == 1


def test_cli_max_suppressions_per_rule():
    """`--max-suppressions-per-rule RULE=N` pins a single rule's
    escape count independently of the global ratchet."""
    path = FIXTURES / "taint_suppressed.py"
    ok = _cli(str(path), "--max-suppressions-per-rule", "replay-taint=1")
    assert ok.returncode == 0, (ok.stdout, ok.stderr)
    # Pinning an unrelated rule at 0 doesn't trip on this file...
    other = _cli(str(path), "--max-suppressions-per-rule",
                 "key-linearity=0")
    assert other.returncode == 0, (other.stdout, other.stderr)
    # ...but pinning the suppressed rule at 0 does.
    over = _cli(str(path), "--max-suppressions-per-rule",
                "replay-taint=0")
    assert over.returncode == 1
    assert "per-rule ratchet" in over.stdout + over.stderr
    # Malformed or unknown specs are a usage error, not a silent pass.
    bad = _cli(str(path), "--max-suppressions-per-rule", "replay-taint")
    assert bad.returncode != 0
    unknown = _cli(str(path), "--max-suppressions-per-rule",
                   "no-such-rule=0")
    assert unknown.returncode != 0


def test_cli_time_budget_gate(monkeypatch):
    """`--time-budget` compares the lint wall time against the budget
    via the runner._monotonic seam (monkeypatched to a fake clock so
    the test is deterministic)."""
    from oryx_tpu.analysis import runner

    ticks = iter([100.0, 107.5])
    monkeypatch.setattr(runner, "_monotonic", lambda: next(ticks))
    rc = runner.main(
        [str(FIXTURES / "donate_clean.py"), "--time-budget", "5.0"]
    )
    assert rc == 1
    ticks = iter([100.0, 100.9])
    monkeypatch.setattr(runner, "_monotonic", lambda: next(ticks))
    rc = runner.main(
        [str(FIXTURES / "donate_clean.py"), "--time-budget", "5.0"]
    )
    assert rc == 0


def test_cli_time_budget_within_budget_for_real(capsys):
    """The repo-wide CI gate: a full lint run must fit the 5s budget
    (run in-process against the real tree; generous margin is the
    point — the gate exists to catch fixpoint blowups, not jitter)."""
    from oryx_tpu.analysis import runner

    rc = runner.main(["--strict", "--time-budget", "5.0"])
    capsys.readouterr()
    assert rc == 0


def test_cli_json_out_writes_artifact(tmp_path):
    """`--json-out` writes the machine-readable report (the CI
    artifact) regardless of the stdout format."""
    report = tmp_path / "report.json"
    path = FIXTURES / "lockorder_pos.py"
    out = _cli(str(path), "--json-out", str(report))
    assert out.returncode == 1  # findings still fail the run
    payload = json.loads(report.read_text())
    assert payload["files"] == 1
    assert {f["rule"] for f in payload["findings"]} == {"lock-order"}
    assert "[lock-order]" in out.stdout  # stdout stayed human-readable


def test_changed_files_widens_on_linter_or_fixture_change(monkeypatch):
    """The --changed-only fast path must widen to a full check when a
    rule module OR a lint fixture changed: either can move findings in
    files that did not change (fixtures pin a rule's contract via
    FIXTURE_RULE_MODULES)."""
    from oryx_tpu.analysis import runner

    def fake_run(changed: list[str]):
        def run(cmd, **kw):
            out = "\n".join(changed) if "diff" in cmd else ""
            return subprocess.CompletedProcess(cmd, 0, stdout=out,
                                               stderr="")
        return run

    # A plain source change keeps the fast path narrow.
    monkeypatch.setattr(
        runner.subprocess, "run",
        fake_run(["oryx_tpu/utils/trace.py"]),
    )
    narrow = runner.changed_files(str(ROOT))
    assert narrow == [str(ROOT / "oryx_tpu" / "utils" / "trace.py")]
    # A rule-module change invalidates per-file checking entirely.
    monkeypatch.setattr(
        runner.subprocess, "run",
        fake_run(["oryx_tpu/analysis/lockorder.py"]),
    )
    assert runner.changed_files(str(ROOT)) is None
    # So does a fixture change — the mapped rule module's contract
    # moved even though the module file itself didn't.
    monkeypatch.setattr(
        runner.subprocess, "run",
        fake_run(["tests/lint_fixtures/atomicity_pos.py",
                  "oryx_tpu/utils/trace.py"]),
    )
    assert runner.changed_files(str(ROOT)) is None
    # And the CLI entry point itself.
    monkeypatch.setattr(
        runner.subprocess, "run",
        fake_run(["scripts/run_oryxlint.py"]),
    )
    assert runner.changed_files(str(ROOT)) is None
    # The dataflow-tier fixtures are in the map too: touching any of
    # them must widen exactly like touching their rule module.
    for fixture in ("tests/lint_fixtures/keylin_pos.py",
                    "tests/lint_fixtures/obligation_suppressed.py",
                    "tests/lint_fixtures/taint_clean.py"):
        monkeypatch.setattr(
            runner.subprocess, "run", fake_run([fixture])
        )
        assert runner.changed_files(str(ROOT)) is None, fixture


def test_fixture_rule_map_covers_every_fixture_prefix():
    """Every fixture on disk maps to a real rule module — a new rule's
    fixtures can't silently fall out of the dependency map."""
    from oryx_tpu.analysis.runner import FIXTURE_RULE_MODULES

    analysis_dir = ROOT / "oryx_tpu" / "analysis"
    for p in FIXTURES.glob("*.py"):
        prefix = p.stem
        for suffix in ("_pos", "_suppressed", "_clean"):
            prefix = prefix.removesuffix(suffix)
        assert prefix in FIXTURE_RULE_MODULES, (
            f"{p.name}: fixture prefix {prefix!r} missing from "
            "FIXTURE_RULE_MODULES"
        )
        assert (analysis_dir / FIXTURE_RULE_MODULES[prefix]).exists()


def test_cli_unknown_rule_errors():
    out = _cli("--rules", "no-such-rule")
    assert out.returncode != 0
    assert "unknown rule" in out.stdout + out.stderr


# ---------------------------------------------------------------------------
# Self-lint: the whole repo is clean (the check_tier1.sh gate)
# ---------------------------------------------------------------------------


@pytest.mark.lint
def test_self_lint_repo_is_clean():
    files = default_files(str(ROOT))
    assert any(f.endswith("scheduler.py") for f in files)
    assert not any("lint_fixtures" in f for f in files)
    res = run_lint(
        ((f, Path(f).read_text()) for f in files), make_checkers()
    )
    assert not res.errors, res.errors
    assert not res.findings, (
        "self-lint regressions:\n"
        + "\n".join(f.format() for f in res.findings)
    )
    # The repo demonstrably USES the machinery: guarded-by fields and
    # hot-path markers exist and deliberate escapes are documented.
    assert res.suppressed > 0


# ---------------------------------------------------------------------------
# Review-pass regressions: directives and markers must live in real
# comments, and suppressed sites must not poison cross-module state
# ---------------------------------------------------------------------------


def test_directives_inside_strings_are_inert():
    src = (
        '"""Docs quoting the syntax: # oryxlint: disable-file=metric-name"""\n'
        "def f(reg):\n"
        "    reg.counter('BadName')\n"
    )
    res = lint_sources(("m.py", src), rules="metric-name")
    assert [f.rule for f in res.findings] == ["metric-name"]


def test_core_module_not_self_disabled_by_its_docstring():
    from oryx_tpu.analysis import core as core_mod

    path = Path(core_mod.__file__)
    pm = core_mod.ParsedModule(str(path), path.read_text())
    assert pm.file_disables == set()


def test_guarded_by_marker_inside_string_is_inert():
    src = (
        "class C:\n"
        '    """docs: self._x = 1  # guarded-by: _lock"""\n'
        "    def f(self):\n"
        "        return self._x\n"
    )
    res = lint_sources(("m.py", src), rules="lock-discipline")
    assert not res.findings


def test_hot_path_marker_between_decorators_and_def():
    """Regression: a marker between the decorator stack and `def` —
    the natural spot when a hot function later gains a decorator —
    was silently ignored, turning the rule off for that function."""
    src = (
        "import functools\n"
        "import numpy as np\n"
        "@functools.cache\n"
        "# hot-path\n"
        "def f(a):\n"
        "    return np.asarray(a)\n"
    )
    res = lint_sources(("m.py", src), rules="host-sync")
    assert [(f.line, f.rule) for f in res.findings] == [(6, "host-sync")]


def test_check_only_restricts_findings_but_not_the_scan():
    """Regression: the `--changed-only` fast path fed only changed
    files into BOTH passes, so a changed caller of an unchanged
    donating callee built an empty donation registry and linted
    clean locally while failing in CI's full run."""
    defs = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('kv',))\n"
        "def consume(params, kv):\n"
        "    return kv\n"
    )
    caller = (
        "from defs import consume\n"
        "def use(params, kv):\n"
        "    out = consume(params, kv)\n"
        "    return kv\n"
    )
    sources = [("defs.py", defs), ("caller.py", caller)]
    res = run_lint(
        sources, make_checkers("use-after-donate"),
        check_only={"caller.py"},
    )
    assert [(f.path, f.line) for f in res.findings] == [("caller.py", 4)]
    # Restricting the check pass to the (clean) defs module reports
    # nothing — the caller's finding belongs to the caller's file.
    res = run_lint(
        sources, make_checkers("use-after-donate"),
        check_only={"defs.py"},
    )
    assert not res.findings


def test_suppressed_clash_site_does_not_poison_kind_map():
    a = (
        "def deliberate_clash(reg):\n"
        "    reg.counter('family_y')  # oryxlint: disable=metric-name\n"
        "    reg.gauge('family_y')  # oryxlint: disable=metric-name\n"
    )
    b = "def correct_usage(reg):\n    reg.gauge('family_y')\n"
    res = lint_sources(("a.py", a), ("b.py", b), rules="metric-name")
    assert not res.findings, [f.format() for f in res.findings]
