"""Sweep state banking + failure taxonomy (scripts/bench_sweep.py): a
watcher-retried sweep must re-pay only retryable gaps — banked successes
and deterministic OOMs are final, truncated state files recover, and
content-hashed keys never serve a stale record for an edited config."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (REPO, os.path.join(REPO, "scripts")):
    if p not in sys.path:
        sys.path.insert(0, p)

import bench_sweep as bs  # noqa: E402


class _Proc:
    def __init__(self, rc, stdout="", stderr=""):
        self.returncode, self.stdout, self.stderr = rc, stdout, stderr


@pytest.fixture
def state_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("SWEEP_STATE_DIR", str(tmp_path))
    return tmp_path


def _fake_run(monkeypatch, proc):
    monkeypatch.setattr(subprocess, "run", lambda *a, **k: proc)


GOOD_LINE = json.dumps({"metric": "m", "value": 42.0, "unit": "tok/s"})


def test_success_banks_and_replays(state_dir, monkeypatch):
    cfg = {"BENCH_REMAT_POLICY": "attn"}
    path = bs._state_path("remat", cfg)
    _fake_run(monkeypatch, _Proc(0, stdout=GOOD_LINE))
    r1 = bs.run_one(cfg, 300, path)
    assert r1["value"] == 42.0 and os.path.exists(path)

    # Replay must not touch subprocess at all.
    def boom(*a, **k):
        raise AssertionError("subprocess must not run on a cache hit")

    monkeypatch.setattr(subprocess, "run", boom)
    assert bs.run_one(cfg, 300, path) == r1


def test_state_keyed_by_content_not_index(state_dir):
    a = bs._state_path("remat", {"BENCH_REMAT_POLICY": "attn"})
    b = bs._state_path("remat", {"BENCH_REMAT_POLICY": "attn_o"})
    assert a is not None and b is not None and a != b
    # Same content, same key — the replay identity the banking relies on.
    assert a == bs._state_path("remat", {"BENCH_REMAT_POLICY": "attn"})


def test_truncated_state_file_recovers(state_dir, monkeypatch):
    cfg = {"BENCH_REMAT_POLICY": "attn"}
    path = bs._state_path("remat", cfg)
    with open(path, "w") as f:
        f.write('{"config": {"BENCH')  # mid-write SIGKILL artifact
    _fake_run(monkeypatch, _Proc(0, stdout=GOOD_LINE))
    r = bs.run_one(cfg, 300, path)
    assert r["value"] == 42.0 and json.load(open(path))["value"] == 42.0


def test_supervisor_oom_is_banked_deterministic(state_dir, monkeypatch):
    cfg = {"BENCH_REMAT_POLICY": "dots"}
    path = bs._state_path("remat", cfg)
    line = json.dumps({"error": "oom", "detail": "Out of memory while ..."})
    _fake_run(monkeypatch, _Proc(1, stdout=line))
    r = bs.run_one(cfg, 300, path)
    assert r is not None and r["error"] == "oom"
    assert json.load(open(path))["error"] == "oom"


def test_oom_counts_as_result_without_state_dir(monkeypatch):
    monkeypatch.delenv("SWEEP_STATE_DIR", raising=False)
    line = json.dumps({"error": "oom", "detail": "Out of memory while ..."})
    _fake_run(monkeypatch, _Proc(1, stdout=line))
    r = bs.run_one({"BENCH_REMAT_POLICY": "dots"}, 300, None)
    assert r is not None and r["error"] == "oom"


def test_bare_resource_exhausted_is_retryable(state_dir, monkeypatch):
    cfg = {"x": "re"}
    path = bs._state_path("remat", cfg)
    _fake_run(
        monkeypatch,
        _Proc(1, stderr="RESOURCE_EXHAUSTED: message larger than max"),
    )
    assert bs.run_one(cfg, 300, path) is None
    assert not os.path.exists(path)


def test_best_env_filters_orphans_and_ooms(state_dir):
    import bench_best as bb

    # Bank two scored records + one OOM, all for CURRENT sweep configs —
    # the OOM must go to a live config or the value-is-None filter leg
    # is never exercised.
    banked = [
        ({"BENCH_REMAT_POLICY": "attn"}, {"value": 90.0}),
        ({"BENCH_REMAT_POLICY": "attn_qkv"}, {"value": 120.0}),
        ({"BENCH_REMAT_POLICY": "attn_o", "BENCH_MOMENT_DTYPE": "bfloat16"},
         {"error": "oom"}),
    ]
    for cfg, rec in banked:
        assert cfg in bs.SWEEPS["remat"], cfg
        bs._bank(bs._state_path("remat", cfg), {"config": cfg, **rec})
    bs._bank(
        bs._state_path("loss_chunk", {"BENCH_LOSS_CHUNK": "256"}),
        {"config": {"BENCH_LOSS_CHUNK": "256"}, "value": 100.0},
    )
    # Orphan: a banked record whose config is NOT in the current SWEEPS
    # (stale hash from an edited list) — must not participate.
    json.dump(
        {"config": {"BENCH_REMAT_POLICY": "legacy"}, "value": 999.0},
        open(os.path.join(str(state_dir), "remat_deadbeef0000.json"), "w"),
    )
    env = bb.best_env(str(state_dir))
    assert env.get("BENCH_REMAT_POLICY") == "attn_qkv"
    assert env.get("BENCH_LOSS_CHUNK") == "256"


def test_tunnel_marker_beats_oom_text(state_dir, monkeypatch):
    cfg = {"x": "flap"}
    path = bs._state_path("remat", cfg)
    _fake_run(
        monkeypatch,
        _Proc(1, stderr="Out of memory ... UNAVAILABLE: socket closed"),
    )
    assert bs.run_one(cfg, 300, path) is None
    assert not os.path.exists(path)
