"""Wide-event request log (utils/request_log.py): schema validation
against REQUEST_EVENT_KEYS, size-capped rotation, one event per
terminal request on every scheduler path (ok / rejected / cancelled /
error), and the /debug/requests?format=jsonl export."""

import json
import threading
import urllib.error
import urllib.request

import pytest

import jax

from oryx_tpu import config as cfg_lib
from oryx_tpu.models import oryx
from oryx_tpu.serve import api_server
from oryx_tpu.serve.pipeline import OryxInference
from oryx_tpu.serve.scheduler import (
    AdmissionRejected,
    ContinuousScheduler,
)
from oryx_tpu.utils.metrics import REQUEST_COST_KEYS, REQUEST_EVENT_KEYS
from oryx_tpu.utils.request_log import (
    RequestLog,
    build_request_event,
)


class FakeTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


@pytest.fixture(scope="module")
def pipe():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    return OryxInference(FakeTokenizer(), params, cfg)


# ---------------------------------------------------------------------------
# Unit: schema + sinks
# ---------------------------------------------------------------------------


def test_registry_is_superset_of_cost_keys():
    assert set(REQUEST_COST_KEYS) < set(REQUEST_EVENT_KEYS)
    # One schema discipline throughout: every key is snake_case.
    import re

    for k in REQUEST_EVENT_KEYS:
        assert re.match(r"^[a-z][a-z0-9_]*$", k), k


def test_build_request_event_validates_keys():
    ev = build_request_event(request_id="r1", status="ok")
    assert ev["schema"] == 1
    assert ev["ts_unix_s"] > 0
    # Deliberately undeclared fields, passed as splats: the static
    # rule lets a splat through (it can't see inside), which is
    # exactly why the RUNTIME validation below must catch it.
    with pytest.raises(ValueError, match="mystery_field"):
        build_request_event(**{"mystery_field": 1})
    with pytest.raises(ValueError, match="REQUEST_EVENT_KEYS"):
        build_request_event(**{"request_id": "r", "BadCase": 2})
    # append() re-validates hand-rolled dicts too.
    log = RequestLog()
    with pytest.raises(ValueError, match="sneaky"):
        log.append({"sneaky": 1})


def test_ring_and_file_with_rotation(tmp_path):
    path = tmp_path / "requests.jsonl"
    log = RequestLog(str(path), keep=4, max_bytes=400)
    for i in range(10):
        log.append(build_request_event(
            request_id=f"r{i}", status="ok", prefill_tokens=i,
        ))
    assert log.total == 10
    snap = log.snapshot()
    assert len(snap) == 4  # ring bounded
    assert [e["request_id"] for e in snap] == ["r6", "r7", "r8", "r9"]
    assert [e["request_id"] for e in log.snapshot(2)] == ["r8", "r9"]
    # The export is one valid JSON object per line, log order.
    lines = log.export_jsonl().strip().splitlines()
    assert [json.loads(ln)["request_id"] for ln in lines] == \
        ["r6", "r7", "r8", "r9"]
    # Rotation: the live file plus ONE .1 generation (older rolls are
    # dropped — disk stays <= ~2x the cap), both complete JSONL with
    # no torn lines, together holding a contiguous SUFFIX of the
    # stream ending at the newest event.
    log.close()
    rolled = tmp_path / "requests.jsonl.1"
    assert rolled.exists()
    recovered = []
    for p in (rolled, path):
        for ln in p.read_text().splitlines():
            recovered.append(json.loads(ln)["request_id"])
    all_ids = [f"r{i}" for i in range(10)]
    assert recovered == all_ids[-len(recovered):]
    assert recovered[-1] == "r9"
    assert len(recovered) >= 4


# ---------------------------------------------------------------------------
# Scheduler integration: one event per terminal path
# ---------------------------------------------------------------------------


def test_every_terminal_path_emits_one_event(pipe, tmp_path):
    log = RequestLog(str(tmp_path / "req.jsonl"))
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        autostart=False, request_log=log, replica_id="rA",
    )
    ok = sched.submit({"question": "hello there"}, 4)
    # Cancelled while queued: the engine hasn't started yet.
    gone = sched.submit({"question": "tell me more"}, 4)
    gone.cancelled = True
    # Invalid at admission (prompt + max_tokens exceeds max_ctx).
    bad = sched.submit({"question": "hi"}, 2048)
    sched.start()
    ok.result(timeout=600)
    with pytest.raises(RuntimeError):
        bad.result(timeout=600)
    sched.close()
    events = {e["request_id"]: e for e in log.snapshot()}
    assert len(events) == 3
    e_ok = events[ok.request_id]
    assert e_ok["status"] == "ok"
    assert e_ok["finish_reason"] in ("stop", "length")
    assert e_ok["replica"] == "rA"
    assert e_ok["engine"] == "continuous"
    assert e_ok["routed"] is False
    assert e_ok["evictions"] == 0
    # The whole cost ledger is embedded, matching the handle's copy.
    for k in REQUEST_COST_KEYS:
        assert e_ok[k] == ok.debug["cost"][k], k
    assert events[gone.request_id]["status"] == "cancelled"
    e_bad = events[bad.request_id]
    assert e_bad["status"] == "error"
    assert e_bad["error_kind"] == "invalid_request"
    # Every event is drawn from the declared schema.
    for e in events.values():
        assert set(e) <= set(REQUEST_EVENT_KEYS)


def test_submit_rejection_emits_rejected_event(pipe):
    log = RequestLog()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        autostart=False, request_log=log, max_queue=1,
    )
    sched.submit({"question": "hello there"}, 4)
    with pytest.raises(AdmissionRejected):
        sched.submit({"question": "tell me more"}, 4)
    events = log.snapshot()
    assert len(events) == 1
    assert events[0]["status"] == "rejected"
    assert events[0]["error_kind"] == "backpressure"
    # Zero-resource ledger, still complete.
    assert events[0]["prefill_tokens"] == 0
    sched.close()


def test_eviction_count_lands_in_event(pipe):
    """An evicted-and-replayed request's event carries evictions >= 1
    (mirrors test_scheduler's engineered page pressure)."""
    import math

    q1, q2 = "hello there", "tell me more"
    chunk, ps = 4, 16
    ids1 = len(pipe._prepare_request({"question": q1})[0])
    ids2 = len(pipe._prepare_request({"question": q2})[0])
    admit1 = math.ceil((ids1 + chunk) / ps)
    admit2 = math.ceil((ids2 + chunk) / ps)
    cap = (admit1 * ps - ids1) + ps
    log = RequestLog()
    sched = ContinuousScheduler(
        pipe, num_slots=2, page_size=ps, chunk=chunk, max_ctx=512,
        num_pages=admit1 + admit2 + 1, autostart=False,
        prefix_cache=False, request_log=log,
    )
    h1 = sched.submit({"question": q1}, cap)
    h2 = sched.submit({"question": q2}, cap)
    sched.start()
    h1.result(timeout=600)
    h2.result(timeout=600)
    sched.close()
    # Engineered page pressure also emits pool_pressure forensics
    # through the same sink (kind-dispatched schema); the request
    # events are the kind-less ones.
    events = {
        e["request_id"]: e for e in log.snapshot() if "kind" not in e
    }
    assert sum(e["evictions"] for e in events.values()) >= 1
    for e in events.values():
        assert e["status"] == "ok"
    pressure = [e for e in log.snapshot() if e.get("kind")]
    assert pressure, "page pressure left no pool_pressure event"
    assert all(e["kind"] == "oom_pressure" for e in pressure)


# ---------------------------------------------------------------------------
# HTTP export
# ---------------------------------------------------------------------------


def test_jsonl_export_over_http(pipe):
    srv = api_server.build_server(
        pipe, port=0, engine="continuous", num_slots=2, page_size=16,
        decode_chunk=4, max_ctx=512, prefill_chunk=32,
        replica_id="r9",
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        rids = []
        for i in range(3):
            req = urllib.request.Request(
                base + "/v1/chat/completions",
                data=json.dumps({
                    "messages": [
                        {"role": "user", "content": f"question {i}?"}
                    ],
                    "max_tokens": 3,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as r:
                rids.append(r.headers.get("X-Request-Id"))
                json.load(r)
        with urllib.request.urlopen(
            base + "/debug/requests?format=jsonl", timeout=30
        ) as r:
            assert r.headers.get("Content-Type") == \
                "application/x-ndjson"
            lines = [ln for ln in r.read().decode().splitlines() if ln]
        events = [json.loads(ln) for ln in lines]
        assert [e["request_id"] for e in events] == rids  # log order
        for e in events:
            assert e["replica"] == "r9"
            assert set(e) <= set(REQUEST_EVENT_KEYS)
        # ?limit= bounds the export.
        with urllib.request.urlopen(
            base + "/debug/requests?format=jsonl&limit=1", timeout=30
        ) as r:
            lim = [ln for ln in r.read().decode().splitlines() if ln]
        assert len(lim) == 1
        assert json.loads(lim[0])["request_id"] == rids[-1]
        # Unknown format is a 400.
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                base + "/debug/requests?format=xml", timeout=30
            )
        assert ei.value.code == 400
        ei.value.close()
    finally:
        srv.scheduler.close()
        srv.shutdown()
