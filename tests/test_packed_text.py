"""Sequence-packed text SFT (train/data.collate_packed_text +
qwen2.forward segment_ids): packing must be a pure LAYOUT change —
identical per-token logits and identical training loss versus the
padded one-sample-per-row batch."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu import config as cfg_lib
from oryx_tpu.constants import IGNORE_INDEX
from oryx_tpu.models import oryx, qwen2
from oryx_tpu.train import data as data_lib
from oryx_tpu.train import step as step_lib


def _examples(cfg, lengths=(11, 7, 5), seed=0):
    rng = np.random.default_rng(seed)
    exs = []
    for n in lengths:
        ids = rng.integers(3, cfg.llm.vocab_size, size=n).astype(np.int64)
        labels = np.full(n, IGNORE_INDEX, np.int64)
        labels[n // 2:] = ids[n // 2:]  # supervise the back half
        exs.append(data_lib.Example(ids, labels, [], "image", 1))
    return exs


def test_packed_logits_match_unpacked():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    exs = _examples(cfg)
    packed = data_lib.collate_packed_text(exs, bucket=32)
    assert packed["token_ids"].shape[0] == 1  # 11+7+5 fits one row
    lg_packed, _ = qwen2.forward(
        params["llm"], cfg.llm,
        input_ids=jnp.asarray(packed["token_ids"]),
        positions=jnp.asarray(packed["positions"]),
        segment_ids=jnp.asarray(packed["text_segment_ids"]),
    )
    lg_packed = np.asarray(lg_packed)
    segs = packed["text_segment_ids"][0]
    off = 0
    for s, ex in enumerate(
        sorted(exs, key=lambda e: -len(e.input_ids)), start=1
    ):
        n = len(ex.input_ids)
        solo, _ = qwen2.forward(
            params["llm"], cfg.llm,
            input_ids=jnp.asarray(ex.input_ids[None]),
        )
        span = np.where(segs == s)[0]
        assert len(span) == n
        np.testing.assert_allclose(
            lg_packed[0, span], np.asarray(solo)[0], rtol=2e-4, atol=2e-4
        )
        off += n


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_packed_attention_impls_agree(impl):
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(1))
    exs = _examples(cfg, lengths=(9, 6))
    packed = data_lib.collate_packed_text(exs, bucket=16)
    lg, _ = qwen2.forward(
        params["llm"], cfg.llm,
        input_ids=jnp.asarray(packed["token_ids"]),
        positions=jnp.asarray(packed["positions"]),
        segment_ids=jnp.asarray(packed["text_segment_ids"]),
        attn_impl=impl,
    )
    ref, _ = qwen2.forward(
        params["llm"], cfg.llm,
        input_ids=jnp.asarray(packed["token_ids"]),
        positions=jnp.asarray(packed["positions"]),
        segment_ids=jnp.asarray(packed["text_segment_ids"]),
        attn_impl="xla",
    )
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(ref), rtol=5e-4, atol=5e-4
    )


def test_packed_loss_matches_padded_collate():
    """The packed batch and the standard padded batch supervise the
    SAME token set, so the masked mean CE must be identical."""
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    exs = _examples(cfg)
    padded = data_lib.collate(exs, base_grid=cfg.vision.base_grid)
    packed = data_lib.collate_packed_text(exs, bucket=32)

    def loss_of(host):
        mb = {k: jnp.asarray(v) for k, v in host.items()}
        (loss, aux), _ = jax.value_and_grad(
            step_lib.microbatch_loss, has_aux=True
        )(params, cfg, mb)
        return float(loss), aux

    l_pad, aux_pad = loss_of(padded)
    l_pack, aux_pack = loss_of(packed)
    assert int(aux_pad["num_tokens"]) == int(aux_pack["num_tokens"])
    assert l_pack == pytest.approx(l_pad, rel=1e-5)


def test_packing_shape_and_errors():
    cfg = cfg_lib.oryx_tiny()
    exs = _examples(cfg, lengths=(20, 20, 20, 4))
    packed = data_lib.collate_packed_text(exs, bucket=32)
    # 20+4 share a row; the other two 20s get their own: 3 rows versus
    # 4 padded rows — fewer rows, zero wasted supervised positions.
    assert packed["token_ids"].shape == (3, 32)
    assert packed["attn_mask"].sum() == 64
    with pytest.raises(ValueError, match="exceeds"):
        data_lib.collate_packed_text(_examples(cfg, lengths=(40,)), bucket=32)
    img_ex = data_lib.Example(
        np.asarray([5, 6]), np.asarray([5, 6]),
        [np.zeros((14, 14, 3), np.uint8)], "image", 1,
    )
    with pytest.raises(ValueError, match="text-only"):
        data_lib.collate_packed_text([img_ex], bucket=32)


def test_segment_ids_rejected_with_cache():
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    segs = jnp.ones((1, 8), jnp.int32)
    cache = qwen2.init_kv_cache(cfg.llm, 1, 32)
    with pytest.raises(ValueError, match="segment_ids"):
        qwen2.forward(
            params["llm"], cfg.llm,
            input_ids=jnp.ones((1, 8), jnp.int32),
            segment_ids=segs, kv_cache=cache,
            write_slots=jnp.zeros((1,), jnp.int32),
            kv_mask=jnp.ones((1, 32), jnp.int32),
        )
    with pytest.raises(ValueError, match="segment_ids"):
        qwen2.forward(
            params["llm"], cfg.llm,
            input_ids=jnp.ones((1, 8), jnp.int32),
            segment_ids=segs, attn_impl="ring",
        )


def test_num_rows_pins_shape():
    """A fixed num_rows keeps the jitted step's shape stable across
    packing outcomes; pad rows are fully masked (zero supervised
    tokens) and never change the loss."""
    cfg = cfg_lib.oryx_tiny()
    exs = _examples(cfg)
    a = data_lib.collate_packed_text(exs, bucket=32, num_rows=4)
    assert a["token_ids"].shape == (4, 32)
    assert a["labels"].dtype == np.int32
    assert (a["text_segment_ids"][1:] == 0).all()
    assert (a["attn_mask"][1:] == 0).all()
    params = oryx.init_params(cfg, jax.random.key(0))
    b = data_lib.collate_packed_text(exs, bucket=32)  # 1 natural row

    def loss_of(host):
        mb = {k: jnp.asarray(v) for k, v in host.items()}
        (loss, _), _ = jax.value_and_grad(
            step_lib.microbatch_loss, has_aux=True
        )(params, cfg, mb)
        return float(loss)

    assert loss_of(a) == pytest.approx(loss_of(b), rel=1e-6)
    with pytest.raises(ValueError, match="num_rows"):
        data_lib.collate_packed_text(
            _examples(cfg, lengths=(30, 30, 30)), bucket=32, num_rows=2
        )


def test_packed_microbatches_train_step():
    """Grad-accum path: packed text microbatches stack to the
    [accum, ...] layout and run the REAL train step."""
    from oryx_tpu.train.optimizer import make_optimizer

    cfg = cfg_lib.oryx_tiny()
    exs = _examples(cfg, lengths=(11, 7, 5, 9, 6, 4))
    host = data_lib.collate_microbatches(
        exs, 2, packed_text=True, pack_bucket=32, pack_num_rows=2,
        base_grid=cfg.vision.base_grid,
    )
    assert host["token_ids"].shape == (2, 2, 32)
    assert host["text_segment_ids"].shape == (2, 2, 32)
    cfg2 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, grad_accum_steps=2)
    )
    params = oryx.init_params(cfg2, jax.random.key(0))
    tx = make_optimizer(cfg2.train, params)
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=tx.init(params),
    )
    batch = {k: jnp.asarray(v) for k, v in host.items()}
    state, metrics = step_lib.train_step(state, batch, cfg2, tx)
    assert np.isfinite(float(metrics["loss"]))
    assert int(metrics["num_tokens"]) == sum(
        len(e.labels) - len(e.labels) // 2 for e in exs
    )


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_packed_loss_fuzz(seed):
    """Property: for ANY sample-length mix, the packed batch's masked
    mean CE equals the padded batch's (same supervised token set)."""
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(seed)
    lengths = tuple(int(n) for n in rng.integers(4, 28, size=8))
    exs = _examples(cfg, lengths=lengths, seed=seed)
    padded = data_lib.collate(
        exs, base_grid=cfg.vision.base_grid, buckets=(32,)
    )
    packed = data_lib.collate_packed_text(
        exs, bucket=32, num_rows=8, buckets=(32,)
    )

    def loss_of(host):
        mb = {k: jnp.asarray(v) for k, v in host.items()}
        loss, aux = step_lib.microbatch_loss(params, cfg, mb)
        return float(loss), int(aux["num_tokens"])

    l_pad, n_pad = loss_of(padded)
    l_pack, n_pack = loss_of(packed)
    assert n_pad == n_pack, lengths
    assert l_pack == pytest.approx(l_pad, rel=2e-5), lengths
