"""Trainer telemetry exporter (train/telemetry.py): a 3-step CPU run
must expose the oryx_train_* series over live HTTP — scraped DURING the
run, monotone between scrapes — with /healthz and /readyz behaving like
a load balancer expects. Plus unit coverage of the goodput/MFU
accounting that doesn't need a real trainer."""

import dataclasses
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from oryx_tpu import config as cfg_lib
from oryx_tpu.train.telemetry import TrainTelemetry, batch_flops
from oryx_tpu.train.trainer import Trainer
from oryx_tpu.utils import flops as flops_lib

from tests.test_metrics_registry import parse_exposition
from tests.test_trainer_modes import _batch

REQUIRED_SERIES = (
    "oryx_train_loss",
    "oryx_train_tokens_per_sec",
    "oryx_train_mfu",
    "oryx_train_goodput_ratio",
    "oryx_train_hbm_live_bytes",
)


def _scrape(port: int) -> dict[str, float]:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30
    ) as r:
        return parse_exposition(r.read().decode())


def _get_json(port: int, path: str):
    """(status, body) without raising on 503."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_three_step_cpu_run_scrapes_live(tmp_path):
    """Acceptance: a 3-step CPU smoke train exposes
    oryx_train_{loss,tokens_per_sec,mfu,goodput_ratio,hbm_live_bytes}
    over HTTP, scraped while the step loop is running, and the step
    counter is monotone across scrapes."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    base = cfg_lib.oryx_tiny()
    cfg = dataclasses.replace(
        base,
        mesh=cfg_lib.MeshConfig(dp=2, fsdp=4, tp=1, sp=1),
        train=dataclasses.replace(
            base.train, num_train_steps=3, log_every=1,
            checkpoint_every=100,
            checkpoint_dir=str(tmp_path / "ckpt"),
        ),
    )
    t = Trainer(
        cfg, metrics_port=0,
        events_path=str(tmp_path / "events.jsonl"),
    )
    assert t.telemetry is not None
    port = t.telemetry.port
    code, body = _get_json(port, "/readyz")
    assert code == 503 and body["ready"] is False  # loop not started yet
    assert _get_json(port, "/healthz") == (200, {"status": "ok"})

    host = _batch(cfg)
    scrapes: list[dict[str, float]] = []

    def feeding():
        # The iterator runs on the fit thread between steps — each
        # yield scrapes the exporter mid-run (steps 2 and 3 observe the
        # previous step's published state).
        for i in range(3):
            if i:
                scrapes.append(_scrape(port))
                code, body = _get_json(port, "/readyz")
                assert code == 200 and body["ready"] is True
            yield host
    try:
        t.fit(feeding(), num_steps=3, resume=False, prefetch=0)
        # The step loop is gone: /readyz must stop saying ready.
        code, body = _get_json(port, "/readyz")
        assert code == 503 and "exited" in body["reason"]
        scrapes.append(_scrape(port))
        final = scrapes[-1]
        for name in REQUIRED_SERIES:
            assert name in final, f"missing {name}"
        assert np.isfinite(final["oryx_train_loss"])
        assert final["oryx_train_tokens_per_sec"] > 0
        assert final["oryx_train_mfu"] == 0.0  # CPU: peak unknown, pinned 0
        assert final["oryx_train_model_flops_per_sec"] > 0
        assert 0 < final["oryx_train_goodput_ratio"] <= 1.0
        assert final["oryx_train_hbm_live_bytes"] > 0  # params are live
        assert final["oryx_train_steps_total"] == 3
        assert final["oryx_train_last_step"] == 3
        assert final["oryx_train_tokens_total"] > 0
        assert final["oryx_train_skipped_steps_total"] == 0
        assert final["oryx_train_step_time_seconds_count"] == 3
        assert final["oryx_train_productive_seconds_total"] > 0
        assert final["oryx_train_lr"] >= 0
        assert final["oryx_train_grad_norm"] > 0
        # Monotone across the in-run scrapes.
        steps_seen = [s["oryx_train_steps_total"] for s in scrapes]
        assert steps_seen == sorted(steps_seen)
        assert steps_seen[0] >= 1 and steps_seen[-1] == 3
        tokens_seen = [s["oryx_train_tokens_total"] for s in scrapes]
        assert tokens_seen == sorted(tokens_seen)
        # Every sample name carries a defensible prefix.
        for name in final:
            base_name = name.split("{")[0]
            assert base_name.startswith(("oryx_train_", "oryx_anomaly_")), \
                name
    finally:
        t.close()


def test_goodput_attribution_unit():
    tel = TrainTelemetry(port=None)
    tel.record_restore(2.0)
    tel.record_step(
        1, {"loss": 1.0, "num_tokens": 100}, step_seconds=1.0,
        data_s=0.2, dispatch_s=0.1, sync_s=0.6, checkpoint_s=0.25,
    )
    r = tel.registry
    assert r.get("productive_seconds_total") == pytest.approx(0.75)
    assert r.get("checkpoint_seconds_total") == pytest.approx(0.25)
    assert r.get("restore_seconds_total") == pytest.approx(2.0)
    assert r.get("data_wait_seconds_total") == pytest.approx(0.2)
    assert r.get("checkpoints_total") == 1
    ratio = r.get("goodput_ratio")
    assert 0 < ratio <= 1.0
    # A skipped step is wall time but NOT goodput.
    tel.record_step(
        2, {"loss": float("nan"), "num_tokens": 100, "skipped": 1},
        step_seconds=1.0,
    )
    assert r.get("productive_seconds_total") == pytest.approx(0.75)
    assert r.get("skipped_steps_total") == 1
    tel.close()


def test_mfu_math_with_known_peak(monkeypatch):
    """With a known chip peak the MFU gauge must equal
    flops / (dt * n_chips * peak) — pinned against the shared 6N model."""
    tel = TrainTelemetry(port=None)
    monkeypatch.setattr(
        flops_lib, "chip_peak_flops", lambda kind: 100e12
    )
    tel.record_step(
        1, {"loss": 1.0, "num_tokens": 100}, step_seconds=2.0,
        flops=40e12,
    )
    n_chips = jax.device_count()
    want = (40e12 / 2.0) / (n_chips * 100e12)
    assert tel.registry.get("mfu") == pytest.approx(want)
    assert tel.registry.get("model_flops_per_sec") == pytest.approx(20e12)
    tel.close()


def test_batch_flops_matches_bench_model():
    """train/telemetry.batch_flops and bench.model_flops_per_step must
    agree exactly — one 6N model, two callers."""
    import bench

    cfg = cfg_lib.oryx_tiny()
    host = _batch(cfg)
    n_llm = flops_lib.count_llm_params(cfg.llm)
    assert batch_flops(cfg, host) == pytest.approx(
        bench.model_flops_per_step(cfg, n_llm, host)
    )
    # The accum axis multiplies tokens AND patches.
    stacked = {k: np.asarray(v)[None] for k, v in host.items()}
    assert batch_flops(cfg, stacked) == pytest.approx(
        batch_flops(cfg, host)
    )
    two = {k: np.stack([v, v]) for k, v in host.items()}
    assert batch_flops(cfg, two) == pytest.approx(2 * batch_flops(cfg, host))


def test_trainer_without_telemetry_has_none(tmp_path):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    cfg = dataclasses.replace(
        cfg_lib.oryx_tiny(),
        mesh=cfg_lib.MeshConfig(dp=2, fsdp=4, tp=1, sp=1),
        train=dataclasses.replace(
            cfg_lib.oryx_tiny().train,
            checkpoint_dir=str(tmp_path / "ckpt"),
        ),
    )
    t = Trainer(cfg)
    assert t.telemetry is None
    t.close()
    # But asking for the halt policy must construct the monitor even
    # with no exporter port — a silently unprotected run is the failure
    # mode the flag exists to prevent.
    t = Trainer(cfg, on_anomaly="halt")
    assert t.telemetry is not None
    assert t.telemetry.server is None  # registry-only, no HTTP thread
    assert t.telemetry.on_anomaly == "halt"
    t.close()
