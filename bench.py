"""Benchmark: Oryx SFT training throughput + 64-frame video-QA latency.

Prints ONE JSON line with the north-star metric (BASELINE.md rows 1-2):

    {"metric": "sft_tokens_per_sec_per_chip", "value": N, "unit": "tok/s",
     "vs_baseline": R, "chip": ..., "hbm_gb": ..., "mfu": ...,
     "geometry": ..., "params_b": ..., "latency_video64_p50_s": ...,
     "latency_video64": {"device_p50_s": ..., "device_spread": ...,
     "e2e_p50_s": ..., ...}, "latency_video256": {...},
     "baseline_source": ...}

On an unreachable TPU the supervisor falls back to a clearly-labeled
CPU PROXY run — the same JSON schema on the tiny geometry with
`"backend": "cpu_proxy"` plus the probe post-mortem
(`tpu_probe_error` / `tpu_probe_attempts`) — so the BENCH trajectory
keeps a trend line even through tunnel outages. Only when the CPU proxy
ALSO fails does the line degrade to
    {"error": "tpu_unavailable", "attempts": N, "probe_timeout_s": ...}
(and the exit code is nonzero) — never a raw traceback. A cpu_proxy
record is a smoke trend point, NOT comparable to TPU rows:
`baseline_source` says `geometry_incomparable` and MFU is absent.

Throughput: the full multimodal SFT step (OryxViT → Dynamic Compressor →
splice → decoder fwd, masked CE, bwd, AdamW; Pallas flash attention on
TPU) on the LARGEST 7B-shaped geometry whose fp32 AdamW training state
fits the detected chip's HBM. Oryx-7B itself needs ~16 bytes/param of
state (~122 GB) — more than any single chip; the geometry ladder below
keeps the 7B shape (head_dim 128, GQA, vocab 152064, attention bias) and
scales width/depth, so tokens/sec/chip and MFU are honest for the chip
being measured. `geometry`/`params_b` in the output say exactly what ran.

MFU uses the standard 6*N*tokens + attention-matmul model FLOPs (remat
recompute NOT counted as useful work) over the chip's peak bf16 FLOPs.

Latency: BASELINE config 3 — 64-frame video QA (16x compression) through
serve/pipeline.OryxInference, greedy, 32 new tokens; p50 over repeats.

`vs_baseline`: BASELINE.json publishes no reference number ("published":
{}), so the bar is DERIVED from first principles (see the "defended
baseline" block below and BASELINE.md "Derivation"): 8xA100 bf16 peak x
the documented HF-Trainer+DeepSpeed multimodal-SFT MFU band / 6N
flops-per-token, divided over the 16 v5e chips of the north-star slice.
When the measured geometry is a sub-7B proxy, the comparable number is
the MFU projection to 7B on this chip (raw proxy tok/s is inflated by
the smaller model); `baseline_source` labels which regime produced the
ratio.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# ---- defended baseline (derivation recorded in BASELINE.md) ---------------
# The reference trains Oryx-7B SFT on 8xA100-80G (HF Trainer + DeepSpeed
# ZeRO, bf16, flash-attn-2; SURVEY.md §6). No published throughput is
# readable (/root/reference is empty, BASELINE.json.published == {}), so
# the bar is derived and carried as a band:
#   tokens/s(total) = n_gpus * peak_bf16 * MFU / flops_per_token
#   flops_per_token ~= 6N (dense decoder fwd+bwd; attention FLOPs and the
#   vision tower push the reference's true flops/token HIGHER, which makes
#   this bar conservative — i.e. harder for us to beat)
# with A100 bf16 peak 312 TFLOP/s, N = 7.6e9 (Qwen2-7B incl. embeddings),
# and MFU band 0.25-0.40 (mid 0.32): the range public HF-Trainer+ZeRO
# multimodal-SFT runs land in on A100 with flash-attn-2 — dense LLM
# pretrain reaches ~0.40-0.50, multimodal SFT loses ground to dynamic
# shapes, per-sample vision towers, and ZeRO comm. The north star is
# matching the 8-GPU TOTAL on a v5e-16 slice, so the per-chip bar
# divides by 16.
A100_BF16_PEAK = 312e12
REF_N_GPUS = 8
REF_PARAMS = 7.6e9
REF_FLOPS_PER_TOK = 6 * REF_PARAMS
REF_MFU_BAND = (0.25, 0.40)
REF_MFU_MID = 0.32
_REF_TOK_S = REF_N_GPUS * A100_BF16_PEAK / REF_FLOPS_PER_TOK  # at MFU 1.0
V5E16_CHIPS = 16
BASELINE_TOK_S_CHIP = _REF_TOK_S * REF_MFU_MID / V5E16_CHIPS  # ~1095
BASELINE_BAND_TOK_S_CHIP = tuple(
    round(_REF_TOK_S * m / V5E16_CHIPS, 1) for m in REF_MFU_BAND
)


def score_vs_baseline(n_llm: float, tok_s_chip: float, mfu, peak):
    """(vs_baseline, baseline_source, projected_7b) — most→least direct:
    a real-7B measurement scores directly per chip; a sub-7B proxy with
    measured MFU scores as the 7B-at-that-MFU projection on this chip's
    peak (the proxy's raw tok/s is inflated by the smaller model's fewer
    flops/token); without a known chip peak (CPU) the raw ratio is
    labeled geometry-incomparable rather than claimed."""
    if n_llm >= 6e9:
        return tok_s_chip / BASELINE_TOK_S_CHIP, \
            "derived_8xA100_mfu_band/direct", None
    if mfu is not None and peak:
        projected = mfu * peak / REF_FLOPS_PER_TOK
        return projected / BASELINE_TOK_S_CHIP, \
            "derived_8xA100_mfu_band/projected_7b_at_measured_mfu", projected
    return tok_s_chip / BASELINE_TOK_S_CHIP, \
        "derived_8xA100_mfu_band/geometry_incomparable", None

# ---- tunnel defense (parent supervisor) -----------------------------------
# The axon TPU tunnel degrades for hours at a time; a bare
# jax.default_backend() then dies with a raw traceback and the round's
# perf artifact records nothing (BENCH_r01/r03). The parent process below
# NEVER imports jax (so it never dials the tunnel or holds a chip claim);
# it probes the backend in a throwaway subprocess with a hard timeout,
# retries across a bounded backoff window, runs the real bench in a
# second subprocess, and — whatever happens — always prints ONE parseable
# JSON line (a metric or {"error": ...}) as its last stdout line.
_BENCH_CHILD_ENV = "ORYX_TPU_BENCH_CHILD"
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
PROBE_BACKOFF_S = int(os.environ.get("BENCH_PROBE_BACKOFF_S", "300"))
CHILD_TIMEOUT_S = int(os.environ.get("BENCH_TIMEOUT_S", "3600"))

# Sync via device_get: block_until_ready is a no-op over the axon
# remote-chip transport.
_PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "v = float(jax.device_get(jnp.sum(jnp.ones((256, 256), jnp.float32)))); "
    "assert v == 65536.0, v; "
    "print('BENCH_PROBE_OK', jax.default_backend(), flush=True)"
)

# Substrings in child stderr that mean "infrastructure, retry" rather
# than "repo bug, fail fast".
_TUNNEL_ERR_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Unable to initialize backend",
    "Connection reset",
    "Socket closed",
)

# Allocator-context OOM markers (XLA: "RESOURCE_EXHAUSTED: Out of memory
# while trying to allocate ..."). Deliberately NOT bare RESOURCE_EXHAUSTED,
# which gRPC also uses for transient transport conditions. Classified here
# in the supervisor, which sees the FULL child output — downstream callers
# (scripts/bench_sweep.py) only see a truncated detail tail where the OOM
# header line is usually sliced off.
_OOM_MARKERS = ("Out of memory", "out of memory")

WARMUP_STEPS = 2
TIMED_STEPS = 5
LATENCY_REPEATS = 5
LATENCY_NEW_TOKENS = 32

# 7B-shaped ladder: (name, llm kwargs). All keep vocab 152064, head_dim
# 128, GQA, attention bias — only width/depth shrink. Ordered largest
# first; the largest whose training state fits HBM is benched.
GEOMETRY_LADDER = (
    ("oryx_7b", dict(
        hidden_size=3584, intermediate_size=18944, num_layers=28,
        num_heads=28, num_kv_heads=4)),
    ("oryx_7b_depth14", dict(
        hidden_size=3584, intermediate_size=18944, num_layers=14,
        num_heads=28, num_kv_heads=4)),
    ("oryx_3b", dict(
        hidden_size=2560, intermediate_size=13696, num_layers=20,
        num_heads=20, num_kv_heads=4)),
    ("oryx_1_5b", dict(
        hidden_size=1536, intermediate_size=8960, num_layers=28,
        num_heads=12, num_kv_heads=2)),
    ("oryx_0_9b", dict(
        hidden_size=1280, intermediate_size=6912, num_layers=24,
        num_heads=10, num_kv_heads=2)),
    ("oryx_0_6b", dict(
        hidden_size=1024, intermediate_size=5504, num_layers=20,
        num_heads=8, num_kv_heads=2)),
)

STATE_BYTES_PER_PARAM = 16  # fp32 params + AdamW mu/nu + fp32 grads
HBM_FRACTION = 0.82  # leave room for activations/logits/workspace


def _llm_cfg(kw):
    from oryx_tpu import config as cfg_lib

    return cfg_lib.LLMConfig(
        vocab_size=152064, head_dim=128, rope_theta=1_000_000.0,
        attention_bias=True, **kw,
    )


def count_llm_params(c) -> int:
    # Shared with the trainer telemetry exporter (utils/flops.py) so
    # bench MFU and /metrics MFU can never disagree on the model.
    # Imported lazily: the supervisor parent must never import
    # oryx_tpu (whose __init__ pulls jax and could dial the tunnel).
    from oryx_tpu.utils import flops as flops_lib

    return flops_lib.count_llm_params(c)


# Fallback HBM per chip kind when memory_stats() is unavailable (the axon
# remote transport does not expose it). Public spec-sheet values.
KNOWN_HBM_GB = (
    ("v6", 32), ("v5p", 95), ("v5e", 16), ("v5 lite", 16),
    ("v5litepod", 16), ("v5", 95), ("v4", 32), ("v3", 16),
)


def chip_info(jax):
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    try:
        stats = dev.memory_stats() or {}
        hbm = int(stats.get("bytes_limit", 0))
    except Exception:
        hbm = 0
    kl = kind.lower()
    if not hbm:
        for tag, gb in KNOWN_HBM_GB:
            if tag in kl:
                hbm = gb * 1024**3
                break
    from oryx_tpu.utils import flops as flops_lib

    return kind, hbm, flops_lib.chip_peak_flops(kind)


def pick_geometry(hbm_bytes: int):
    budget = hbm_bytes * HBM_FRACTION
    for name, kw in GEOMETRY_LADDER:
        c = _llm_cfg(kw)
        if count_llm_params(c) * STATE_BYTES_PER_PARAM < budget:
            return name, c
    name, kw = GEOMETRY_LADDER[-1]
    return name, _llm_cfg(kw)


def _bench_cfg(backend: str, hbm_bytes: int):
    from oryx_tpu import config as cfg_lib

    if backend == "tpu" and not os.environ.get("BENCH_SMALL"):
        geo_name, llm = pick_geometry(hbm_bytes)
        vision = cfg_lib.VisionConfig(
            hidden_size=768,
            intermediate_size=2048,
            num_layers=6,
            num_heads=12,
            head_dim=64,
            patch_size=14,
            base_grid=27,
        )
        batch_size, seq_bucket, img_patches_side = 8, (2048,), 16
        comp_heads = 12
    else:
        geo_name, llm = "tiny", cfg_lib.tiny_llm()
        vision = cfg_lib.tiny_vision()
        batch_size, seq_bucket, img_patches_side = 2, (128,), 4
        comp_heads = 4
    # Sweepable geometry knobs (scripts/bench_sweep.py "batch"): more
    # tokens/step amortizes per-step overhead where the memory freed by
    # bf16 moments / thin remat policies allows. Honored on every
    # backend — a CPU sweep must measure the requested geometry, not
    # silently bank distinct records for the same default tiny shape.
    if os.environ.get("BENCH_BATCH"):
        batch_size = int(os.environ["BENCH_BATCH"])
    if os.environ.get("BENCH_SEQ"):
        seq_bucket = (int(os.environ["BENCH_SEQ"]),)
    cfg = cfg_lib.OryxConfig(
        llm=llm,
        vision=vision,
        compressor=cfg_lib.CompressorConfig(num_heads=comp_heads),
        dtype="bfloat16",
        # Pallas flash attention on the real chip; portable XLA path on CPU.
        attn_impl="pallas" if backend == "tpu" else "xla",
    )
    # Remat policy (utils/remat.py), BENCH_REMAT_POLICY = none|block|
    # dots|attn|attn_qkv|attn_o. TPU default "attn": saving the flash
    # outputs + lse
    # (~0.7 GB at this geometry) skips the kernel recompute in the
    # backward — measured +4% step time over "block" on v5e, while
    # "dots" exceeds HBM by ~5 GB (TPU_VALIDATION.md).
    pol = os.environ.get(
        "BENCH_REMAT_POLICY", "attn" if cfg.attn_impl == "pallas" else ""
    )
    chunk = os.environ.get("BENCH_LOSS_CHUNK")  # scripts/bench_sweep.py
    train_updates = {}
    if pol:
        train_updates.update(
            remat=pol != "none",
            remat_policy=pol if pol != "none" else "block",
        )
    if chunk:
        train_updates.update(loss_chunk=int(chunk))
    if os.environ.get("BENCH_MOMENT_DTYPE"):  # float32|bfloat16
        train_updates.update(moment_dtype=os.environ["BENCH_MOMENT_DTYPE"])
    if train_updates:
        import dataclasses

        cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, **train_updates)
        )
    return geo_name, cfg, batch_size, seq_bucket, img_patches_side


def _make_batch(cfg, batch_size, seq_bucket, img_side):
    from oryx_tpu.constants import IGNORE_INDEX, IMAGE_TOKEN_INDEX
    from oryx_tpu.models import splice
    from oryx_tpu.ops import packing

    rng = np.random.default_rng(0)
    p = cfg.vision.patch_size
    images = [
        rng.standard_normal((img_side * p, img_side * p, 3)).astype(np.float32)
        for _ in range(batch_size)
    ]
    packed = packing.pack_images(
        images,
        patch_size=p,
        base_grid=cfg.vision.base_grid,
        side_factors=2,
    )
    slots = splice.query_slots(packed)
    vis_tokens = slots[0][1]
    # Fill the sequence bucket: prompt + image + supervised text.
    text_len = seq_bucket[-1] - vis_tokens - 1
    ids, labels = [], []
    for _ in range(batch_size):
        text = rng.integers(3, cfg.llm.vocab_size, size=text_len)
        row = np.concatenate([text[:8], [IMAGE_TOKEN_INDEX], text[8:]])
        lab = np.full(row.shape, IGNORE_INDEX, np.int64)
        lab[9 + 8:] = row[9 + 8:]
        ids.append(row)
        labels.append(lab)
    batch = splice.build_mm_batch(ids, slots, labels=labels, buckets=seq_bucket)
    return {
        "patches": packed.patches,
        "segment_ids": packed.segment_ids,
        "pos_coords": packed.pos_coords,
        "region_ids": packed.region_ids,
        "q_region_ids": packed.q_region_ids,
        "token_ids": batch.token_ids,
        "visual_idx": batch.visual_idx,
        "is_visual": batch.is_visual.astype(np.bool_),
        "attn_mask": batch.attn_mask,
        "positions": batch.positions,
        "labels": batch.labels,
    }


def model_flops_per_step(cfg, n_llm_params, host) -> float:
    """Analytic model FLOPs for one SFT step (the shared 6N + attention
    model in utils/flops.py — remat recompute excluded)."""
    from oryx_tpu.utils import flops as flops_lib

    B, T = host["token_ids"].shape
    return flops_lib.train_step_flops(
        cfg, n_llm_params, batch=B, seq_len=T,
        patch_tokens=int(host["segment_ids"].shape[-1]),
    )


class _CharTokenizer:
    """Deterministic host-side tokenizer for the latency bench (no
    pretrained vocab available offline)."""

    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 50000) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 50000)


def make_video_request(pipe, cfg, num_frames: int):
    """One deterministic video-QA request, prepped + packed the way the
    serving pipeline does it. Shared by the end-to-end latency bench and
    scripts/bench_components.py so the component breakdown measures the
    SAME request shape the e2e number comes from.

    Returns (frames, question, mm_batch, staged_arrays)."""
    from oryx_tpu.models import oryx, splice
    from oryx_tpu.ops import packing

    rng = np.random.default_rng(0)
    frames = [
        rng.integers(0, 255, size=(224, 224, 3), dtype=np.uint8)
        for _ in range(num_frames)
    ]
    question = "what happens?"
    ids, images, factors, caps = pipe._prepare_request({
        "question": question, "images": frames, "is_video": True,
    })
    packed = packing.pack_raw_images(
        images, patch_size=cfg.vision.patch_size,
        base_grid=cfg.vision.base_grid, side_factors=factors,
        max_patches=caps,
    )
    batch = splice.build_mm_batch([ids], splice.query_slots(packed))
    return frames, question, batch, oryx.stage_mm_arrays(packed, batch)


def bench_video_latency(params, cfg, num_frames: int = 64) -> dict:
    """Video-QA latency through the serving pipeline, split into two
    components (VERDICT r3 #4 — the tunnel-noise fix):

      device_p50_s  — the compiled ViT+compressor+splice+prefill+decode
                      program, inputs pre-placed on device, synced by
                      fetching the tiny num_generated vector. Over the
                      axon transport this still pays ONE round trip per
                      rep, but none of the host preprocessing or frame
                      upload — run-to-run spread is reported so the
                      number is auditable as a regression gate.
      e2e_p50_s     — full pipe.chat_video wall clock (preprocess + pack
                      + upload + decode + detokenize), what a user sees.

    num_frames=64 is BASELINE config 3; 256 is the north-star long-video
    case (16x compression, shared patch budget across frames)."""
    import jax

    from oryx_tpu.models import oryx
    from oryx_tpu.ops import packing
    from oryx_tpu.serve.pipeline import OryxInference

    pipe = OryxInference(_CharTokenizer(), params, cfg)
    frames, question, batch, arrays = make_video_request(pipe, cfg, num_frames)

    # --- device-only component ------------------------------------------
    cache_len = packing.round_up_bucket(
        batch.token_ids.shape[1] + LATENCY_NEW_TOKENS
    )
    key = jax.random.key(0)
    run = lambda: oryx._jit_mm_generate(
        params, cfg, arrays, LATENCY_NEW_TOKENS, cache_len, key,
        pipe.stop_sequences,
    )
    _, num, _ = run()
    jax.device_get(num)  # warmup compile + one sync
    dev = []
    for _ in range(LATENCY_REPEATS):
        t0 = time.perf_counter()
        _, num, _ = run()
        jax.device_get(num)
        dev.append(time.perf_counter() - t0)

    # --- end-to-end component -------------------------------------------
    pipe.chat_video(frames, question, max_new_tokens=LATENCY_NEW_TOKENS)
    e2e = []
    for _ in range(max(3, LATENCY_REPEATS // 2)):
        t0 = time.perf_counter()
        pipe.chat_video(frames, question, max_new_tokens=LATENCY_NEW_TOKENS)
        e2e.append(time.perf_counter() - t0)

    dev, e2e = np.asarray(dev), np.asarray(e2e)
    return {
        "device_p50_s": round(float(np.percentile(dev, 50)), 4),
        "device_spread": round(
            float((dev.max() - dev.min()) / max(np.percentile(dev, 50), 1e-9)),
            3,
        ),
        "e2e_p50_s": round(float(np.percentile(e2e, 50)), 4),
        "patch_bucket": int(arrays["patches"].shape[0]),
        "seq_bucket": int(batch.token_ids.shape[1]),
    }


def _probe_once() -> tuple[bool, str]:
    """Touch the default backend in a throwaway subprocess with a hard
    timeout. Returns (ok, tail-of-output)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {PROBE_TIMEOUT_S}s"
    out = (proc.stdout or "") + (proc.stderr or "")
    ok = proc.returncode == 0 and "BENCH_PROBE_OK" in out
    return ok, "\n".join(out.strip().splitlines()[-8:])


def _run_bench_child(extra_env=None) -> tuple[int | None, str, str]:
    """Run the real bench in a subprocess → (rc, stdout, stderr); rc None
    means killed on timeout. extra_env overrides (the CPU-proxy fallback
    pins JAX_PLATFORMS=cpu)."""
    env = dict(os.environ)
    env[_BENCH_CHILD_ENV] = "1"
    env.update(extra_env or {})
    # Persistent compile cache (same default as dryrun_multichip): the
    # driver's end-of-round bench pays the 0.6B-geometry compile on one
    # CPU core + tunnel latency; a warm cache from the agenda's earlier
    # runs turns that into seconds.
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired as e:
        def txt(x):
            return x.decode() if isinstance(x, bytes) else (x or "")
        return None, txt(e.stdout), (
            txt(e.stderr) + f"\n# bench child killed after {CHILD_TIMEOUT_S}s"
        )
    return proc.returncode, proc.stdout or "", proc.stderr or ""


def _find_json_line(out: str) -> str | None:
    """Last stdout line that parses as the bench's JSON contract."""
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and ("metric" in d or "error" in d):
            return line
    return None


def _emit_error(kind: str, detail: str, attempts: int) -> None:
    print(json.dumps({
        "error": kind,
        "detail": detail[-2000:],
        "attempts": attempts,
        "probe_timeout_s": PROBE_TIMEOUT_S,
        "probe_backoff_s": PROBE_BACKOFF_S,
    }))
    sys.exit(1)


def _supervise() -> None:
    """Parent: probe → bench child → retry across tunnel flaps. Never
    imports jax; never exits without a parseable JSON line."""
    last = ""
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        ok, tail = _probe_once()
        print(f"# probe attempt {attempt}/{PROBE_ATTEMPTS}: "
              f"{'ok' if ok else 'FAILED'}", flush=True)
        if ok:
            rc, out, err = _run_bench_child()
            line = _find_json_line(out)
            if rc == 0 and line:
                # Pass the child's stdout through (latency notes etc.) and
                # its stderr phase markers (wall-clock per phase — the only
                # record of where slow-tunnel time went), then re-print the
                # JSON line so it is LAST on stdout.
                phases = [
                    ln for ln in err.splitlines() if ln.startswith("# [")
                ]
                body = "\n".join(phases + [
                    ln for ln in out.strip().splitlines() if ln.strip() != line
                ])
                if body:
                    print(body)
                print(line)
                return
            both = out + "\n" + err
            # Keep the phase markers in the post-mortem even when the
            # interesting tail is 15 lines of XLA warnings — they are the
            # whole point on a killed/hung child. Budget both pieces so
            # _emit_error's detail[-2000:] can never slice the phases off.
            phases = [ln for ln in both.splitlines() if ln.startswith("# [")]
            tail = "\n".join(both.strip().splitlines()[-15:])[-1400:]
            last = "\n".join(phases)[-500:] + ("\n" if phases else "") + tail
            infra = rc is None or any(m in both for m in _TUNNEL_ERR_MARKERS)
            # "oom" is deterministic for the configuration: retrying the
            # identical run cannot succeed (sweep callers bank it instead
            # of looping). It takes precedence over the infra markers — an
            # OOM that also tears the tunnel connection down is still an
            # OOM, and re-paying compile+OOM per retry buys nothing.
            oom = any(m in both for m in _OOM_MARKERS)
            if oom or not infra:
                _emit_error("oom" if oom else "bench_failed", last, attempt)
        else:
            last = tail
        if attempt < PROBE_ATTEMPTS:
            print(f"# backing off {PROBE_BACKOFF_S}s before retry", flush=True)
            time.sleep(PROBE_BACKOFF_S)
    _cpu_proxy_fallback(last)


def _cpu_proxy_fallback(probe_error: str) -> None:
    """TPU unreachable after every probe attempt: run the bench on the
    CPU backend (tiny geometry — `_bench_cfg` picks it for any non-TPU
    backend) and emit the SAME JSON schema labeled
    `"backend": "cpu_proxy"`. The trajectory keeps a trend line through
    tunnel outages; `baseline_source` marks the row geometry-incomparable
    so nobody mistakes the proxy for a chip measurement. Only when even
    the proxy fails does the old {"error": "tpu_unavailable"} shape
    (and nonzero exit) survive."""
    print("# tpu unreachable; falling back to CPU proxy bench", flush=True)
    rc, out, err = _run_bench_child(
        extra_env={"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    )
    line = _find_json_line(out)
    if rc == 0 and line:
        d = json.loads(line)
        d["backend"] = "cpu_proxy"
        d["tpu_probe_error"] = probe_error[-500:]
        d["tpu_probe_attempts"] = PROBE_ATTEMPTS
        phases = [ln for ln in err.splitlines() if ln.startswith("# [")]
        body = "\n".join(phases + [
            ln for ln in out.strip().splitlines() if ln.strip() != line
        ])
        if body:
            print(body)
        print(json.dumps(d))
        return
    both = out + "\n" + err
    tail = "\n".join(both.strip().splitlines()[-10:])[-900:]
    _emit_error(
        "tpu_unavailable",
        probe_error[-900:] + "\n# cpu proxy also failed:\n" + tail,
        PROBE_ATTEMPTS,
    )


def _phase(msg: str) -> None:
    """Timestamped phase marker on stderr. The supervisor captures child
    stderr (including the partial read when it kills on timeout), so these
    tell a post-mortem *where* a slow-tunnel run was stuck — a 33-minute
    silent hang with 14 s of CPU is indistinguishable from a livelock
    without them."""
    print(f"# [{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from oryx_tpu.models import oryx
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer

    _phase("backend init")
    backend = jax.default_backend()
    n_chips = jax.device_count()
    chip, hbm, peak = chip_info(jax)
    geo_name, cfg, batch_size, seq_bucket, img_side = _bench_cfg(backend, hbm)
    n_llm = count_llm_params(cfg.llm)
    host = _make_batch(cfg, batch_size, seq_bucket, img_side)
    batch = {k: jnp.asarray(v)[None] for k, v in host.items()}  # accum=1

    _phase(f"init params ({geo_name})")
    params = oryx.init_params(cfg, jax.random.key(0))
    tx = make_optimizer(cfg.train, params)
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
    )

    # NOTE: sync via device_get, not block_until_ready — the latter is a
    # no-op over the remote-chip (axon) transport and fakes the timing.
    tokens_per_step = int(np.sum(host["attn_mask"]))
    _phase("train_step compile + warmup")
    for _ in range(WARMUP_STEPS):
        state, metrics = step_lib.train_step(state, batch, cfg, tx)
    float(jax.device_get(metrics["loss"]))

    _phase("train_step timed loop")
    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = step_lib.train_step(state, batch, cfg, tx)
    loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss} in bench step")

    step_time = dt / TIMED_STEPS
    tok_s_chip = tokens_per_step / step_time / n_chips
    mfu = None
    if peak:
        flops = model_flops_per_step(cfg, n_llm, host)
        mfu = round(flops / step_time / (n_chips * peak), 4)

    del state, metrics, batch  # free HBM for the inference latency bench
    lat64 = lat256 = None
    if not os.environ.get("BENCH_NO_LATENCY"):
        try:
            # Fresh params: the originals were donated into train_step.
            _phase("latency: 64-frame video-QA")
            params = oryx.init_params(cfg, jax.random.key(0))
            lat64 = bench_video_latency(params, cfg, 64)
        # fault-boundary: keep the primary metric even if this fails
        except Exception as e:
            print(f"# latency bench failed: {e!r}")
        # 256-frame north-star case (BASELINE config 3): real chips only
        # by default (256 frames through the tiny CPU config is all
        # compile time); BENCH_VIDEO256=1 forces, =0 skips.
        want256 = os.environ.get(
            "BENCH_VIDEO256", "1" if backend == "tpu" else "0"
        ) == "1"
        if want256 and lat64 is not None:
            try:
                _phase("latency: 256-frame video-QA (north star)")
                lat256 = bench_video_latency(params, cfg, 256)
            except Exception as e:  # OOM here is itself a finding
                print(f"# 256-frame latency bench failed: {e!r}")
                lat256 = {"error": f"{type(e).__name__}: {e}"[:300]}

    # int8 weight-only serving latency (utils/quant.py): decode is
    # HBM-bandwidth-bound, so halving weight bytes should show directly
    # in device_p50 — measured on the same 64-frame case.
    lat64_q8 = None
    want_q8 = os.environ.get(
        "BENCH_INT8", "1" if backend == "tpu" else "0"
    ) == "1"
    if want_q8 and lat64 is not None:
        try:
            from oryx_tpu.utils.quant import quantize_params

            _phase("latency: 64-frame video-QA, int8 weights")
            params = quantize_params(params)
            lat64_q8 = bench_video_latency(params, cfg, 64)
        except Exception as e:  # attempted-and-failed must be auditable
            print(f"# int8 latency bench failed: {e!r}")
            lat64_q8 = {"error": f"{type(e).__name__}: {e}"[:300]}

    vs_baseline, baseline_source, projected_7b = score_vs_baseline(
        n_llm, tok_s_chip, mfu, peak
    )
    print(json.dumps({
        "metric": "sft_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tok/s",
        "backend": backend,
        "vs_baseline": round(vs_baseline, 4),
        "baseline_source": baseline_source,
        "baseline_tok_s_chip": round(BASELINE_TOK_S_CHIP, 1),
        "baseline_band_tok_s_chip": list(BASELINE_BAND_TOK_S_CHIP),
        "projected_7b_tok_s_chip": projected_7b and round(projected_7b, 1),
        "chip": chip,
        "hbm_gb": round(hbm / 1024**3, 1) if hbm else None,
        "geometry": geo_name,
        "params_b": round(n_llm / 1e9, 2),
        "step_time_s": round(step_time, 3),
        "mfu": mfu,
        "latency_video64_p50_s": lat64 and lat64["e2e_p50_s"],
        "latency_video64": lat64,
        "latency_video256": lat256,
        "latency_video64_int8": lat64_q8,
    }))


if __name__ == "__main__":
    # CPU-pinned runs (CI, smoke) don't dial the tunnel — no defense
    # needed; run in-process. Everything else goes through the supervisor.
    if (
        os.environ.get(_BENCH_CHILD_ENV) == "1"
        or os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    ):
        main()
    else:
        _supervise()
