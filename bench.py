"""Benchmark: Oryx SFT training throughput (tokens/sec/chip).

Runs the full multimodal SFT step — OryxViT → Dynamic Compressor → splice →
decoder forward, masked CE, backward, AdamW — under jit on whatever backend
is available, and prints ONE JSON line:

    {"metric": "sft_tokens_per_sec_per_chip", "value": N, "unit": "tok/s",
     "vs_baseline": R}

The model geometry scales with the backend: a ~350M-param decoder (Qwen2-
style GQA, bf16 compute, remat) with the SigLIP-class vision tower on TPU;
a tiny config on CPU so the script stays runnable anywhere.

`vs_baseline` is measured against BASELINE.json's published numbers when
present; BASELINE.json currently publishes none (`"published": {}`), so the
ratio uses the documented placeholder below (an 8xA100 Oryx-7B SFT
tokens/sec/chip estimate) and is to be re-anchored when real reference
numbers appear.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

# Placeholder reference throughput (tokens/sec/chip) for Oryx-7B SFT on
# 8xA100; BASELINE.json `published` is empty. Replace when measured.
PLACEHOLDER_BASELINE_TOK_S_CHIP = 2000.0

WARMUP_STEPS = 2
TIMED_STEPS = 5


def _bench_cfg(backend: str):
    from oryx_tpu import config as cfg_lib

    if backend == "tpu" and not os.environ.get("BENCH_SMALL"):
        llm = cfg_lib.LLMConfig(
            vocab_size=16384,
            hidden_size=1536,
            intermediate_size=4096,
            num_layers=12,
            num_heads=12,
            num_kv_heads=4,
            head_dim=128,
            attention_bias=True,
        )
        vision = cfg_lib.VisionConfig(
            hidden_size=768,
            intermediate_size=2048,
            num_layers=6,
            num_heads=12,
            head_dim=64,
            patch_size=14,
            base_grid=27,
        )
        batch_size, seq_bucket, img_patches_side = 8, (2048,), 16
        comp_heads = 12
    else:
        llm = cfg_lib.tiny_llm()
        vision = cfg_lib.tiny_vision()
        batch_size, seq_bucket, img_patches_side = 2, (128,), 4
        comp_heads = 4
    cfg = cfg_lib.OryxConfig(
        llm=llm,
        vision=vision,
        compressor=cfg_lib.CompressorConfig(num_heads=comp_heads),
        dtype="bfloat16",
        # Pallas flash attention on the real chip; portable XLA path on CPU.
        attn_impl="pallas" if backend == "tpu" else "xla",
    )
    return cfg, batch_size, seq_bucket, img_patches_side


def _make_batch(cfg, batch_size, seq_bucket, img_side):
    from oryx_tpu.constants import IGNORE_INDEX, IMAGE_TOKEN_INDEX
    from oryx_tpu.models import splice
    from oryx_tpu.ops import packing

    rng = np.random.default_rng(0)
    p = cfg.vision.patch_size
    images = [
        rng.standard_normal((img_side * p, img_side * p, 3)).astype(np.float32)
        for _ in range(batch_size)
    ]
    packed = packing.pack_images(
        images,
        patch_size=p,
        base_grid=cfg.vision.base_grid,
        side_factors=2,
    )
    slots = splice.query_slots(packed)
    vis_tokens = slots[0][1]
    # Fill the sequence bucket: prompt + image + supervised text.
    text_len = seq_bucket[-1] - vis_tokens - 1
    ids, labels = [], []
    for _ in range(batch_size):
        text = rng.integers(3, cfg.llm.vocab_size, size=text_len)
        row = np.concatenate([text[:8], [IMAGE_TOKEN_INDEX], text[8:]])
        lab = np.full(row.shape, IGNORE_INDEX, np.int64)
        lab[9 + 8:] = row[9 + 8:]
        ids.append(row)
        labels.append(lab)
    batch = splice.build_mm_batch(ids, slots, labels=labels, buckets=seq_bucket)
    return {
        "patches": packed.patches,
        "segment_ids": packed.segment_ids,
        "pos_coords": packed.pos_coords,
        "region_ids": packed.region_ids,
        "q_region_ids": packed.q_region_ids,
        "token_ids": batch.token_ids,
        "visual_idx": batch.visual_idx,
        "is_visual": batch.is_visual.astype(np.bool_),
        "attn_mask": batch.attn_mask,
        "positions": batch.positions,
        "labels": batch.labels,
    }


def main() -> None:
    import jax
    import jax.numpy as jnp

    from oryx_tpu.models import oryx
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer

    backend = jax.default_backend()
    n_chips = jax.device_count()
    cfg, batch_size, seq_bucket, img_side = _bench_cfg(backend)
    host = _make_batch(cfg, batch_size, seq_bucket, img_side)
    batch = {k: jnp.asarray(v)[None] for k, v in host.items()}  # accum=1

    params = oryx.init_params(cfg, jax.random.key(0))
    tx = make_optimizer(cfg.train, params)
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
    )

    # NOTE: sync via device_get, not block_until_ready — the latter is a
    # no-op over the remote-chip (axon) transport and fakes the timing.
    tokens_per_step = int(np.sum(host["attn_mask"]))
    for _ in range(WARMUP_STEPS):
        state, metrics = step_lib.train_step(state, batch, cfg, tx)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = step_lib.train_step(state, batch, cfg, tx)
    loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss} in bench step")

    tok_s_chip = tokens_per_step * TIMED_STEPS / dt / n_chips
    print(json.dumps({
        "metric": "sft_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tok/s",
        "vs_baseline": round(tok_s_chip / PLACEHOLDER_BASELINE_TOK_S_CHIP, 4),
    }))


if __name__ == "__main__":
    main()
