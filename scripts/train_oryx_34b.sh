#!/usr/bin/env bash
# Oryx-34B (Yi-34B backbone) SFT on a v5e-64 pod: fsdp=64 + grad accum.
# The reference's 34B path is the same train_mem.py under zero3.json
# (SURVEY.md §2b "ZeRO-3 for 34B/long-video").
set -euo pipefail
cd "$(dirname "$0")/.."

DATA=${DATA:?path to conversation-records json}
TOKENIZER=${TOKENIZER:?path to Yi tokenizer dir}
HF_LLM=${HF_LLM:-}
HF_VISION=${HF_VISION:-}

python -m oryx_tpu.train.cli \
  --config scripts/configs/oryx_34b_sft.json \
  --data "$DATA" \
  --tokenizer-path "$TOKENIZER" \
  ${HF_LLM:+--hf-llm "$HF_LLM"} \
  ${HF_VISION:+--hf-vision "$HF_VISION"} \
  --sharding fsdp \
  --metrics-path logs/oryx34b_metrics.jsonl \
  --output-dir models/oryx34b-sft \
  "$@"
