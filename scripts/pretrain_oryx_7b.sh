#!/usr/bin/env bash
# Stage-1 projector pretraining: only the Dynamic Compressor / projector
# trains (tune="projector_only"), LLM + vision tower frozen, plain
# template — the reference's `tune_mm_mlp_adapter` stage producing
# `mm_projector.bin` (SURVEY.md §2 "Training entry", §3.3). The resulting
# projector npz feeds --projector in the SFT stage.
set -euo pipefail
cd "$(dirname "$0")/.."

DATA=${DATA:?path to caption-records json}
TOKENIZER=${TOKENIZER:?path to Qwen2 tokenizer dir}
HF_LLM=${HF_LLM:?HF safetensors dir (Qwen2-7B-Instruct)}
HF_VISION=${HF_VISION:?HF safetensors dir (SigLIP-family tower)}

python -m oryx_tpu.train.cli \
  --config scripts/configs/oryx_7b_pretrain.json \
  --data "$DATA" \
  --tokenizer-path "$TOKENIZER" \
  --hf-llm "$HF_LLM" \
  --hf-vision "$HF_VISION" \
  --template plain \
  --sharding fsdp \
  --metrics-path logs/oryx7b_pretrain_metrics.jsonl \
  --output-dir models/oryx7b-pretrain \
  "$@"
