#!/usr/bin/env bash
# Oryx-7B SFT on a v5e-16 slice (fsdp=16, ZeRO-3-equivalent).
# Reference-equivalent launch: `deepspeed --num_gpus 8 oryx/train/train_mem.py
#   --deepspeed scripts/zero3.json --model_name_or_path Qwen/Qwen2-7B-Instruct
#   --vision_tower <oryx-vit> ...` (SURVEY.md §1 L6). One process per HOST;
# on a pod each host runs this same command (jax.distributed auto-rendezvous).
set -euo pipefail
cd "$(dirname "$0")/.."

DATA=${DATA:?path to conversation-records json}
TOKENIZER=${TOKENIZER:?path to Qwen2 tokenizer dir}
HF_LLM=${HF_LLM:-}          # HF safetensors dir (Qwen2-7B-Instruct)
HF_VISION=${HF_VISION:-}    # HF safetensors dir (SigLIP-family tower)

python -m oryx_tpu.train.cli \
  --config scripts/configs/oryx_7b_sft.json \
  --data "$DATA" \
  --tokenizer-path "$TOKENIZER" \
  ${HF_LLM:+--hf-llm "$HF_LLM"} \
  ${HF_VISION:+--hf-vision "$HF_VISION"} \
  --sharding fsdp \
  --metrics-path logs/oryx7b_metrics.jsonl \
  --output-dir models/oryx7b-sft \
  "$@"
