#!/usr/bin/env python
"""CPU microbench: shared-prefix KV cache on vs off, repeated-system-
prompt workload through the continuous scheduler.

Measures what the prefix cache is FOR — prefill tokens actually
computed (`oryx_serving_prefill_tokens_total`) and mean time-to-first-
token — on a workload where every request carries the same long system
prompt and a short unique question (the dominant real traffic shape).
The acceptance bar for the change is a >= 2x reduction in prefill
tokens computed with the cache on, with mean TTFT no worse; the token
ratio is exact and deterministic, the TTFT comparison is wall-clock
(noisy on loaded CI, reported always, gated only in full mode).

    JAX_PLATFORMS=cpu python scripts/bench_prefix_cache.py \
        [--requests 16 --sys-chars 400 --cap 6] \
        [--num-slots 4 --chunk 4 --page-size 16 --prefill-chunk 64] \
        [--smoke] [--json out.json]

--smoke shrinks the workload for the CI gate (scripts/check_tier1.sh)
and exits nonzero if the token ratio is under 2x.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class _CharTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


SYS = (
    "You are a meticulous multimodal assistant for the Oryx serving "
    "stack. Study the provided context carefully before answering; "
    "keep replies short, factual and grounded in what you can see. "
)


def _workload(n: int, sys_chars: int) -> list[str]:
    prefix = (SYS * (sys_chars // len(SYS) + 1))[:sys_chars]
    return [f"{prefix} question number {i}: what now?" for i in range(n)]


def _run_engine(pipe, questions, cap, args, *, prefix_cache: bool) -> dict:
    from oryx_tpu.serve.scheduler import ContinuousScheduler
    from oryx_tpu.utils.metrics import ServingMetrics

    metrics = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=args.num_slots, page_size=args.page_size,
        chunk=args.chunk, max_ctx=args.max_ctx,
        num_pages=args.num_pages, metrics=metrics, autostart=False,
        prefill_chunk=args.prefill_chunk or None,
        prefix_cache=prefix_cache,
    )
    handles = [sched.submit({"question": q}, cap) for q in questions]
    t0 = time.monotonic()
    sched.start()
    replies = [h.result(timeout=600)[0] for h in handles]
    wall = time.monotonic() - t0
    sched._check_pool_invariant()
    sched.close()
    ttfts = [h.debug["ttft_s"] for h in handles]
    return {
        "replies": replies,
        "prefill_tokens": metrics.get("prefill_tokens_total"),
        "hit_tokens": metrics.get("prefix_cache_hit_tokens_total"),
        "miss_tokens": metrics.get("prefix_cache_miss_tokens_total"),
        "cache_entries": metrics.get("prefix_cache_entries"),
        "cache_pages": metrics.get("prefix_cache_pages"),
        "evicted_pages": metrics.get("prefix_cache_evicted_pages_total"),
        "mean_ttft_s": sum(ttfts) / len(ttfts),
        "max_ttft_s": max(ttfts),
        "wall_s": wall,
    }


def run(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--sys-chars", type=int, default=400)
    ap.add_argument("--cap", type=int, default=6)
    ap.add_argument("--num-slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--prefill-chunk", type=int, default=64)
    ap.add_argument("--max-ctx", type=int, default=1024)
    ap.add_argument("--num-pages", type=int, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="small workload + hard >=2x token-ratio gate")
    ap.add_argument("--json", default=None, help="also write results here")
    args = ap.parse_args(argv)
    if args.smoke:
        args.requests = min(args.requests, 6)
        args.sys_chars = min(args.sys_chars, 240)

    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve.pipeline import OryxInference

    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_CharTokenizer(), params, cfg)
    questions = _workload(args.requests, args.sys_chars)
    if args.num_pages is None:
        # Generous pool: the bench measures recompute avoidance, not
        # eviction dynamics.
        per = -(-(len(questions[0]) + 80 + args.cap) // args.page_size)
        args.num_pages = per * (args.num_slots + 2)

    cold = _run_engine(
        pipe, questions, args.cap, args, prefix_cache=False
    )
    warm = _run_engine(
        pipe, questions, args.cap, args, prefix_cache=True
    )
    assert warm.pop("replies") == cold.pop("replies"), (
        "prefix cache changed a reply — bit-parity broken"
    )

    ratio = cold["prefill_tokens"] / max(warm["prefill_tokens"], 1)
    out = {
        "workload": {
            "requests": args.requests, "sys_chars": args.sys_chars,
            "cap": args.cap, "prefill_chunk": args.prefill_chunk,
            "page_size": args.page_size, "num_slots": args.num_slots,
        },
        "no_prefix_cache": cold,
        "prefix_cache": warm,
        "prefill_tokens_ratio": ratio,
        "ttft_improvement": cold["mean_ttft_s"] / max(
            warm["mean_ttft_s"], 1e-9
        ),
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    if ratio < 2.0:
        print(json.dumps(out, indent=2))
        print(
            f"FAIL: prefill-token reduction {ratio:.2f}x < 2x",
            file=sys.stderr,
        )
        raise SystemExit(1)
    if not args.smoke and warm["mean_ttft_s"] > cold["mean_ttft_s"]:
        print(json.dumps(out, indent=2))
        print(
            "FAIL: mean TTFT did not improve "
            f"({warm['mean_ttft_s']:.4f}s vs {cold['mean_ttft_s']:.4f}s)",
            file=sys.stderr,
        )
        raise SystemExit(1)
    return out


if __name__ == "__main__":
    print(json.dumps(run(sys.argv[1:]), indent=2))
