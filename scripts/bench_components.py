"""Component-wise latency breakdown of the video-QA serving path.

Where do the 64/256-frame milliseconds go? bench.py's latency cases time
the fused program end-to-end; this script times the pipeline's stages as
separate jitted programs on the same request (same packing, same shapes):

  encode   — ViT + Dynamic Compressor + splice into the text stream
             (oryx.mm_embeds: the whole visual front-end)
  prefill  — decoder forward over the spliced embeds (qwen2.forward,
             no cache), the prompt-processing cost
  decode   — per-token decode cost, measured as the slope between two
             _jit_mm_generate windows (16 vs 48 new tokens) so the
             shared prefill+encode cost cancels

Prints one JSON line per component plus a summary line. Sync follows
bench.py's convention: fetch a tiny output via device_get (over the axon
tunnel, block_until_ready is a no-op). CPU runs exercise the same code
with meaningless numbers; real numbers ride scripts/tpu_round4.sh.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPS = int(os.environ.get("COMPONENT_REPS", "10"))


def _p50_spread(ts):
    ts = np.asarray(ts)
    p50 = float(np.percentile(ts, 50))
    return round(p50, 4), round(float((ts.max() - ts.min()) / max(p50, 1e-9)), 3)


def time_fn(fn, sync, reps=REPS):
    sync(fn())  # warmup/compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn())
        ts.append(time.perf_counter() - t0)
    return _p50_spread(ts)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from bench import _CharTokenizer, _bench_cfg, chip_info, make_video_request
    from oryx_tpu.models import oryx, qwen2
    from oryx_tpu.ops import packing
    from oryx_tpu.serve.pipeline import OryxInference

    backend = jax.default_backend()
    _, hbm, _ = chip_info(jax)
    _, cfg, *_ = _bench_cfg(backend, hbm)
    num_frames = int(os.environ.get("COMPONENT_FRAMES", "64"))
    new_tokens = (16, 48)

    params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_CharTokenizer(), params, cfg)
    _, _, batch, arrays = make_video_request(pipe, cfg, num_frames)
    T = int(batch.token_ids.shape[1])
    out = {
        "metric": "component_latency_p50_s", "unit": "s",
        "frames": num_frames, "prompt_tokens": T,
        "patch_bucket": int(arrays["patches"].shape[0]),
        "backend": backend,
    }

    # encode: whole visual front-end (jit cached in oryx.mm_embeds).
    enc = lambda: oryx.mm_embeds(params, cfg, arrays)
    p50, spread = time_fn(enc, lambda e: jax.device_get(e[:1, :1]))
    out["encode_p50_s"], out["encode_spread"] = p50, spread

    embeds = enc()
    positions = jnp.asarray(batch.positions)
    kv_mask = jnp.asarray(batch.attn_mask)

    # prefill: decoder forward over the spliced embeds, no cache.
    @jax.jit
    def _prefill(params_llm, embeds):
        h, _ = qwen2.forward(
            params_llm, cfg.llm, inputs_embeds=embeds, positions=positions,
            kv_mask=kv_mask, attn_impl=cfg.attn_impl,
            compute_dtype=oryx.compute_dtype(cfg), return_hidden=True,
        )
        return h
    p50, spread = time_fn(
        lambda: _prefill(params["llm"], embeds),
        lambda h: jax.device_get(h[:1, :1, :1]),
    )
    out["prefill_p50_s"], out["prefill_spread"] = p50, spread

    # decode: slope between two generate windows (shared cost cancels).
    # No stop sequences, and the slope is only reported when BOTH windows
    # ran full length — the early-exit decode loop (models/generate.
    # _decode_while) otherwise stops at EOS and the slope measures noise.
    totals, full = {}, True
    for n in new_tokens:
        cache_len = packing.round_up_bucket(T + n)
        run = lambda: oryx._jit_mm_generate(
            params, cfg, arrays, n, cache_len, jax.random.key(0), None
        )
        p50, spread = time_fn(
            run, lambda r: jax.device_get(r[1]), reps=max(3, REPS // 2)
        )
        generated = int(jax.device_get(run()[1])[0])
        full &= generated == n
        totals[n] = p50
        out[f"generate{n}_p50_s"], out[f"generate{n}_spread"] = p50, spread
        out[f"generate{n}_tokens"] = generated
    n1, n2 = new_tokens
    out["decode_per_token_s"] = (
        round((totals[n2] - totals[n1]) / (n2 - n1), 5) if full else None
    )
    if not full:
        out["note"] = "early EOS: decode windows not full, slope unreliable"

    print(json.dumps(out))


if __name__ == "__main__":
    main()
