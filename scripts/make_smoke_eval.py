"""Generate (and optionally run) the self-contained smoke eval benchmark.

The L7 eval harness (oryx_tpu/eval/harness.py) mirrors the reference's
lmms-eval flow (SURVEY.md §3.5) but no real benchmark data exists on this
box, so this script builds a tiny fully-offline one: synthetic frames with
a VISUALLY decidable answer (a solid colored square on gray), MCQ records
in the native task schema, and — with --run — the whole real pipeline:
build a model dir + byte-level HF tokenizer on disk, then invoke
`eval.harness.main` exactly as a user would from the CLI.

    python scripts/make_smoke_eval.py --out assets/smoke_eval
    python scripts/make_smoke_eval.py --out /tmp/smoke --run \
        --result assets/smoke_eval/result_cpu.json

Accuracy with random weights: chance-level (0.25, 4 options) under
--scoring loglikelihood; 0.0 under the default generate mode (the random
model emits no parseable answer letter). Either way the committed result
JSON documents the harness producing a real accuracy from the real
pipeline, not the model's skill.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COLORS = {
    "red": (200, 40, 40),
    "green": (40, 180, 60),
    "blue": (40, 70, 200),
    "yellow": (220, 200, 40),
}
OPTIONS = list(COLORS)


def _frame(color: str, offset: int = 0, size: int = 64) -> np.ndarray:
    img = np.full((size, size, 3), 128, np.uint8)
    s = size // 3
    y = x = size // 2 - s // 2 + offset
    img[y : y + s, x : x + s] = COLORS[color]
    return img


def build_task(out_dir: str) -> str:
    """Write media + task.jsonl under out_dir; returns the task path."""
    from PIL import Image

    media = os.path.join(out_dir, "media")
    os.makedirs(media, exist_ok=True)
    rng = np.random.default_rng(0)
    records = []
    for i in range(8):
        color = OPTIONS[i % len(OPTIONS)]
        video = i >= 4
        if video:
            d = os.path.join(media, f"vid{i}")
            os.makedirs(d, exist_ok=True)
            for f in range(4):
                Image.fromarray(_frame(color, offset=2 * f - 3)).save(
                    os.path.join(d, f"frame_{f}.png")
                )
            media_key = {"video": f"media/vid{i}"}
            q = "What color is the moving square in the video?"
        else:
            p = os.path.join(media, f"img{i}.png")
            Image.fromarray(_frame(color)).save(p)
            media_key = {"image": f"media/img{i}.png"}
            q = "What color is the square?"
        opts = list(OPTIONS)
        rng.shuffle(opts)
        records.append({
            "id": f"smoke-{i}",
            "question": q,
            "options": opts,
            "answer": "ABCD"[opts.index(color)],
            "meta": {"kind": "video" if video else "image"},
            **media_key,
        })
    task = os.path.join(out_dir, "task.jsonl")
    with open(task, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return task


def build_model_dir(out_dir: str) -> str:
    """Tiny random-weight model + a real on-disk HF tokenizer (byte-level
    BPE built offline — ids < 300 fit the tiny 512 vocab), loadable by
    serve.builder.load_pipeline with no network."""
    import jax
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders
    from transformers import PreTrainedTokenizerFast

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve import builder

    d = os.path.join(out_dir, "model")
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    builder.save_pretrained(d, cfg, params)

    alphabet = pre_tokenizers.ByteLevel.alphabet()
    vocab = {ch: i for i, ch in enumerate(sorted(alphabet))}
    tk = Tokenizer(models.BPE(vocab=vocab, merges=[]))
    tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tk.decoder = decoders.ByteLevel()
    PreTrainedTokenizerFast(tokenizer_object=tk).save_pretrained(d)
    return d


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True, help="benchmark output dir")
    ap.add_argument(
        "--run", action="store_true",
        help="also build a tiny model dir and run eval.harness.main",
    )
    ap.add_argument("--result", default=None, help="result json path")
    ap.add_argument("--num-frames", type=int, default=4)
    ap.add_argument(
        "--scoring", default="generate", choices=["generate", "loglikelihood"],
        help="harness scoring mode; loglikelihood gives chance-level "
        "accuracy on the random-weight smoke model (generate-mode answer "
        "parsing scores 0.0 there)",
    )
    args = ap.parse_args(argv)

    task = build_task(args.out)
    print(f"task written: {task}")
    if not args.run:
        return
    model_dir = build_model_dir(args.out)
    from oryx_tpu.eval import harness

    harness.main([
        "--model-path", model_dir,
        "--task", task,
        "--media-root", args.out,
        "--num-frames", str(args.num_frames),
        "--max-new-tokens", "4",
        "--by", "kind",
        "--scoring", args.scoring,
        *( ["--output", args.result] if args.result else [] ),
    ])


if __name__ == "__main__":
    main()
