"""Perf-knob sweeps over the north-star bench, one subprocess per
configuration (kernel tile sizes and remat policy are baked at trace
time, so in-process sweeps would read stale settings).

    python scripts/bench_sweep.py remat   # none|block|attn|attn_qkv|attn_o
                                          # ("dots" OOMs at the bench shape)
    python scripts/bench_sweep.py loss_chunk     # CE chunk 64..512
    python scripts/bench_sweep.py bwd_blocks     # flash backward tiles

Prints one JSON line per configuration (the bench's own schema) plus a
final best-by-tok/s line. Run on the real chip; each configuration pays
one compile (cache via JAX_COMPILATION_CACHE_DIR). Measured v5e results
live in TPU_VALIDATION.md — re-run after kernel or remat changes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SWEEPS = {
    "remat": [
        {"BENCH_REMAT_POLICY": p}
        for p in ("none", "block", "attn", "attn_qkv", "attn_o")
    ],
    "loss_chunk": [{"BENCH_LOSS_CHUNK": str(c)} for c in (64, 128, 256, 512)],
    "bwd_blocks": [
        {"ORYX_FLASH_BWD_BLOCK_Q": q, "ORYX_FLASH_BWD_BLOCK_K": k}
        for q, k in (("0", "0"), ("512", "1024"), ("1024", "1024"),
                     ("1024", "2048"))
    ],
    "fwd_blocks": [
        {"ORYX_FLASH_BLOCK_Q": q, "ORYX_FLASH_BLOCK_K": k}
        for q, k in (("512", "512"), ("512", "1024"), ("1024", "512"),
                     ("1024", "1024"))
    ],
}


def run_one(extra_env: dict[str, str], timeout: int) -> dict | None:
    # One probe attempt and a child budget inside our own timeout: the
    # supervisor's full 3x5-min retry ladder would eat the per-config
    # window before the bench ever ran. A flap costs one config, and the
    # next config probes again anyway.
    env = {
        **os.environ,
        "BENCH_NO_LATENCY": "1",
        "BENCH_PROBE_ATTEMPTS": "1",
        "BENCH_TIMEOUT_S": str(max(60, timeout - 150)),
        **extra_env,
    }
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({"config": extra_env, "error": "timeout"}))
        return None
    line = next(
        (l for l in reversed(out.stdout.splitlines())
         if l.startswith("{")), None,
    )
    if out.returncode != 0 or line is None:
        print(json.dumps({
            "config": extra_env, "error": (out.stderr or out.stdout)[-400:],
        }))
        return None
    rec = {"config": extra_env, **json.loads(line)}
    print(json.dumps(rec))
    return rec


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "remat"
    if which not in SWEEPS:
        raise SystemExit(f"unknown sweep {which!r}; have {sorted(SWEEPS)}")
    timeout = int(os.environ.get("SWEEP_TIMEOUT_S", "600"))
    results = [r for e in SWEEPS[which] if (r := run_one(e, timeout))]
    if results:
        best = max(results, key=lambda r: r.get("value", 0.0))
        print(json.dumps({"best": best["config"], "value": best["value"]}))
    if len(results) < len(SWEEPS[which]):
        # Nonzero exit when any config failed so a retrying caller
        # (tunnel_watch -> tpu_round4 step .ok markers) re-runs the sweep
        # rather than banking a partial grid as done.
        raise SystemExit(1)


if __name__ == "__main__":
    main()
