"""Perf-knob sweeps over the north-star bench, one subprocess per
configuration (kernel tile sizes and remat policy are baked at trace
time, so in-process sweeps would read stale settings).

    python scripts/bench_sweep.py remat   # none|block|attn|attn_qkv
                                          # + attn_o:bf16-moments ("dots"
                                          # and plain attn_o OOM at the
                                          # bench shape — AOT-proven)
    python scripts/bench_sweep.py loss_chunk     # CE chunk 64..512
    python scripts/bench_sweep.py bwd_blocks     # flash backward tiles

Prints one JSON line per configuration (the bench's own schema) plus a
final best-by-tok/s line. Run on the real chip; each configuration pays
one compile (cache via JAX_COMPILATION_CACHE_DIR). Measured v5e results
live in TPU_VALIDATION.md — re-run after kernel or remat changes.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Infra-vs-bug taxonomy shared with the bench supervisor. The supervisor
# classifies OOMs itself (it sees the full child output; see bench.py
# _OOM_MARKERS) and emits {"error": "oom"} — deterministic for the
# configuration, so the result is banked rather than retried: a
# watcher-driven re-run must not loop forever on a config that OOMs by
# construction (e.g. remat policies that exceed HBM at the bench shape).
# The text markers below are only the fallback for non-supervised runs
# (CPU in-process mode), requiring allocator context — bare
# RESOURCE_EXHAUSTED is also a transient gRPC transport status.
from bench import (  # noqa: E402
    _OOM_MARKERS,
    _TUNNEL_ERR_MARKERS,
    _find_json_line,
)

SWEEPS = {
    "remat": [
        # Plain attn_o is NOT in the grid: the real-compiler AOT of the
        # exact bench program says 16.00 GB vs 15.75 usable
        # (TPU_VALIDATION round 5) — a guaranteed OOM would burn ~10 min
        # of chip window to bank what is already proven. Its bf16-moment
        # variant (14.62 GB, fits) carries the policy's upside.
        {"BENCH_REMAT_POLICY": p}
        for p in ("none", "block", "attn", "attn_qkv")
    ] + [
        {"BENCH_REMAT_POLICY": "attn_o", "BENCH_MOMENT_DTYPE": "bfloat16"},
    ],
    "loss_chunk": [{"BENCH_LOSS_CHUNK": str(c)} for c in (64, 128, 256, 512)],
    "bwd_blocks": [
        {"ORYX_FLASH_BWD_BLOCK_Q": q, "ORYX_FLASH_BWD_BLOCK_K": k}
        for q, k in (("0", "0"), ("512", "1024"), ("1024", "1024"),
                     ("1024", "2048"))
    ],
    "fwd_blocks": [
        {"ORYX_FLASH_BLOCK_Q": q, "ORYX_FLASH_BLOCK_K": k}
        for q, k in (("512", "512"), ("512", "1024"), ("1024", "512"),
                     ("1024", "1024"))
    ],
    # Token-volume sweep: more tokens/step amortizes per-step overhead.
    # The >8 rows only stand a chance with the bf16-moment headroom, so
    # they carry it; OOMs bank as final negative results.
    "batch": [
        {"BENCH_BATCH": "8"},
        {"BENCH_BATCH": "12", "BENCH_MOMENT_DTYPE": "bfloat16"},
        {"BENCH_BATCH": "16", "BENCH_MOMENT_DTYPE": "bfloat16"},
        {"BENCH_BATCH": "8", "BENCH_SEQ": "4096",
         "BENCH_MOMENT_DTYPE": "bfloat16"},
    ],
}


def _state_path(
    which: str, extra_env: dict[str, str], state_dir: str | None = None
) -> str | None:
    """Keyed by a hash of the config CONTENT, not its list index — a
    later edit/reorder of a SWEEPS list must never serve a stale banked
    record for a different config."""
    d = state_dir or os.environ.get("SWEEP_STATE_DIR")
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    h = hashlib.sha1(
        json.dumps(extra_env, sort_keys=True).encode()
    ).hexdigest()[:12]
    return os.path.join(d, f"{which}_{h}.json")


def _bank(state: str, rec: dict) -> None:
    """Atomic write: the agenda's `timeout --kill-after` can SIGKILL this
    process mid-dump; a truncated state file must not wedge retries."""
    tmp = state + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, state)


def run_one(extra_env: dict[str, str], timeout: int,
            state: str | None = None) -> dict | None:
    """Returns the banked record, or None when the config should be
    retried (tunnel flap / timeout). Deterministic failures (OOM) are
    banked as error records — retrying them cannot succeed."""
    if state and os.path.exists(state):
        try:
            rec = json.load(open(state))
        except ValueError:  # truncated by a mid-write kill: re-run
            os.remove(state)
        else:
            print(json.dumps({**rec, "cached": True}))
            return rec
    # One probe attempt and a child budget inside our own timeout: the
    # supervisor's full 3x5-min retry ladder would eat the per-config
    # window before the bench ever ran. A flap costs one config, and the
    # next config probes again anyway.
    env = {
        **os.environ,
        "BENCH_NO_LATENCY": "1",
        "BENCH_PROBE_ATTEMPTS": "1",
        "BENCH_TIMEOUT_S": str(max(60, timeout - 150)),
        **extra_env,
    }
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({"config": extra_env, "error": "timeout"}))
        return None
    line = _find_json_line(out.stdout or "")
    if out.returncode != 0 or line is None:
        both = (out.stderr or "") + (out.stdout or "")
        # Prefer the supervisor's own classification (it saw the full,
        # untruncated child output); fall back to allocator-context text
        # markers for non-supervised (in-process CPU) runs.
        err_json = {}
        if line is not None:
            try:
                err_json = json.loads(line)
            except ValueError:
                pass
        deterministic = err_json.get("error") == "oom" or (
            any(m in both for m in _OOM_MARKERS)
            and not any(m in both for m in _TUNNEL_ERR_MARKERS)
        )
        rec = {
            "config": extra_env,
            "error": err_json.get("error") or (out.stderr or out.stdout)[-400:],
            **({"detail": err_json["detail"][-400:]}
               if err_json.get("detail") else {}),
        }
        print(json.dumps(rec))
        if deterministic:
            # Banked as a (negative) result with or without a state dir:
            # a deterministic failure must count toward sweep completion,
            # or a retrying caller loops forever on a config that OOMs by
            # construction.
            if state:
                _bank(state, rec)
            return rec
        return None
    rec = {"config": extra_env, **json.loads(line)}
    print(json.dumps(rec))
    if state:
        _bank(state, rec)
    return rec


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "remat"
    if which not in SWEEPS:
        raise SystemExit(f"unknown sweep {which!r}; have {sorted(SWEEPS)}")
    timeout = int(os.environ.get("SWEEP_TIMEOUT_S", "600"))
    results = [
        r for e in SWEEPS[which]
        if (r := run_one(e, timeout, _state_path(which, e)))
    ]
    scored = [r for r in results if "value" in r]
    if scored:
        best = max(scored, key=lambda r: r.get("value", 0.0))
        print(json.dumps({"best": best["config"], "value": best["value"]}))
    if len(results) < len(SWEEPS[which]):
        # Nonzero exit ONLY for retryable gaps (tunnel flap/timeout) so a
        # retrying caller (tunnel_watch -> tpu_round4 .ok markers) re-runs
        # just those; with SWEEP_STATE_DIR set, banked configs (including
        # deterministic OOMs) are never re-paid.
        raise SystemExit(1)


if __name__ == "__main__":
    main()
