#!/usr/bin/env python
"""CPU microbench: window batcher vs continuous scheduler on a
skewed-length workload.

Measures the WASTED-STEP FRACTION — decode steps spent on rows that are
already finished (window batcher: every short row rides the decode
bucket to its end; scheduler: only the chunk overhang + idle slots) —
plus slot occupancy, on the tiny CPU model. The acceptance bar for the
continuous-batching change is a >= 2x drop in wasted fraction
(tests/test_scheduler.py runs this as a `slow` test).

    JAX_PLATFORMS=cpu python scripts/bench_serving_sched.py \
        [--shorts 10 --longs 4 --short-cap 4 --long-cap 24] \
        [--num-slots 4 --chunk 4 --page-size 16] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class _CharTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


def _workload(shorts: int, longs: int, short_cap: int, long_cap: int,
              seed: int = 0):
    """Skewed request mix, shuffled with a fixed seed (arrival order
    matters for both engines)."""
    import numpy as np

    reqs = [("short request %d" % i, short_cap) for i in range(shorts)]
    reqs += [("long request %d" % i, long_cap) for i in range(longs)]
    rng = np.random.default_rng(seed)
    rng.shuffle(reqs)
    return reqs


def run(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--shorts", type=int, default=10)
    ap.add_argument("--longs", type=int, default=4)
    ap.add_argument("--short-cap", type=int, default=4)
    ap.add_argument("--long-cap", type=int, default=24)
    ap.add_argument("--num-slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--max-ctx", type=int, default=512)
    ap.add_argument("--json", default=None, help="also write results here")
    args = ap.parse_args(argv)

    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve.api_server import Batcher
    from oryx_tpu.serve.pipeline import OryxInference
    from oryx_tpu.serve.scheduler import ContinuousScheduler
    from oryx_tpu.utils.metrics import ServingMetrics

    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_CharTokenizer(), params, cfg)
    reqs = _workload(args.shorts, args.longs, args.short_cap, args.long_cap)

    # ---- window batcher (the legacy engine) -----------------------------
    wm = ServingMetrics()
    batcher = Batcher(
        pipe, window=0.2, max_batch=args.num_slots, metrics=wm
    )
    pending = [
        batcher.submit({"question": q}, cap) for q, cap in reqs
    ]
    for p in pending:
        assert p.done.wait(timeout=600)
        assert p.error is None, p.error
    w_total = wm.get("decode_steps_total")
    w_wasted = wm.get("decode_steps_wasted")

    # ---- continuous scheduler -------------------------------------------
    sm = ServingMetrics()
    sched = ContinuousScheduler(
        pipe, num_slots=args.num_slots, page_size=args.page_size,
        chunk=args.chunk, max_ctx=args.max_ctx, metrics=sm,
        autostart=False,
    )
    handles = [sched.submit({"question": q}, cap) for q, cap in reqs]
    sched.start()
    for h in handles:
        h.result(timeout=600)
    sched.close()
    s_total = sm.get("decode_steps_total")
    s_wasted = sm.get("decode_steps_wasted")

    w_frac = w_wasted / max(w_total, 1)
    s_frac = s_wasted / max(s_total, 1)
    out = {
        "workload": {
            "shorts": args.shorts, "longs": args.longs,
            "short_cap": args.short_cap, "long_cap": args.long_cap,
        },
        "window": {
            "decode_steps_total": w_total,
            "decode_steps_wasted": w_wasted,
            "wasted_frac": w_frac,
        },
        "scheduler": {
            "decode_steps_total": s_total,
            "decode_steps_wasted": s_wasted,
            "wasted_frac": s_frac,
            "slot_occupancy_final": sm.get("slot_occupancy"),
            "step_utilization": sm.get("decode_step_utilization"),
            "chunks": sm.get("chunks"),
            "admitted": sm.get("admitted"),
            "evicted": sm.get("evicted"),
        },
        "wasted_frac_ratio": w_frac / max(s_frac, 1e-9),
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    return out


if __name__ == "__main__":
    print(json.dumps(run(sys.argv[1:]), indent=2))
