"""CI well-formedness gate for the serving observability surface.

Runs one battery of endpoint checks against a serving TARGET — a bare
replica (api_server) or the prefix-affinity router (serve/router.py)
fronting several — detected from the target's own /metrics:

  * GET /healthz — 200 liveness;
  * GET /readyz — 200 with ready:true while the target can serve (the
    load-balancer probe that replaces spending a real completion);
  * GET /metrics — exact Prometheus content type
    (`text/plain; version=0.0.4`), every metric name carries the
    target's prefix (`oryx_serving_` on a replica, `oryx_router_` on
    the router; the cross-source `oryx_anomaly_` family is the one
    deliberate exception), the build_info gauge is present with
    revision + engine labels. Replicas must expose the HBM gauges;
    the router instead must expose `/metrics/aggregate` where every
    replica sample line carries an injected `replica=` label
    (including the HBM gauges, per backend);
  * GET /debug/requests — valid JSON, the request we sent is recorded
    (the router merges its replicas' flight recorders); ?limit=
    bounds the response, ?state=done returns only finished requests
    and every one carries a COMPLETE per-request cost ledger
    (utils/metrics.REQUEST_COST_KEYS), a bogus state is a 400;
  * GET /debug/trace?id= — valid Chrome trace JSON covering
    queue_wait/prefill/decode_chunk (the router locates the replica
    that served the id);
  * a latency histogram read back through the SHARED quantile helpers
    (utils/metrics.parse_prom_histogram + histogram_quantile — the
    same math scripts/loadgen.py reports with): finite, positive,
    ordered p50 <= p99. Replica: `oryx_serving_ttft_seconds`; router:
    `oryx_router_upstream_ttfb_seconds`;
  * prefix cache under a shared-prefix burst — hit/miss counters,
    entries/pages gauges, eviction counter and the prefill chunk-size
    histogram present and well-formed, and hit_tokens actually moved
    (summed across replicas through the aggregation endpoint when the
    target is the router).

Modes:

    # self-boot a tiny CPU replica (the default; wired into
    # scripts/check_tier1.sh)
    python scripts/check_serving_endpoints.py

    # the same gate against any live target — a bare replica or a
    # router front-end
    python scripts/check_serving_endpoints.py --base-url http://host:port

    # 2-replica router smoke: boots two tiny replicas + a router,
    # runs the full gate against the ROUTER, then asserts prefix
    # AFFINITY — the shared-prefix burst must land on one replica
    # (its oryx_serving_prefix_cache_hit_tokens_total dominates)
    python scripts/check_serving_endpoints.py --router-smoke

Exit 0 = all good; nonzero prints what broke.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""


class _Tokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def _get(base: str, path: str, timeout: float = 30.0):
    return urllib.request.urlopen(base + path, timeout=timeout)


def boot_tiny_server(replica_id: str | None = None):
    """One tiny-geometry continuous-engine CPU replica; returns the
    (unstarted threads aside) live server."""
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve import api_server
    from oryx_tpu.serve.pipeline import OryxInference

    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_Tokenizer(), params, cfg)
    srv = api_server.build_server(
        pipe, port=0, engine="continuous", num_slots=2, page_size=16,
        decode_chunk=4, max_ctx=512, prefill_chunk=32,
        replica_id=replica_id,
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _base_of(srv) -> str:
    return f"http://127.0.0.1:{srv.server_address[1]}"


SYSMSG = ("You are a careful assistant. Study the context and "
          "answer briefly. " * 2)


def _completion(base: str, messages, max_tokens: int = 4,
                request_id: str | None = None) -> str:
    headers = {"Content-Type": "application/json"}
    if request_id:
        headers["X-Request-Id"] = request_id
    req = urllib.request.Request(
        base + "/v1/chat/completions",
        data=json.dumps({
            "messages": messages, "max_tokens": max_tokens,
        }).encode(),
        headers=headers,
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        rid = r.headers.get("X-Request-Id")
        json.load(r)
    return rid


def _labeled_total(text: str, family: str) -> float:
    """Sum of a family's samples across any labels (the aggregated
    multi-replica view)."""
    total = 0.0
    for m in re.finditer(
        rf"^{re.escape(family)}(?:\{{[^}}]*\}})? ([0-9.e+-]+)$",
        text, re.M,
    ):
        total += float(m.group(1))
    return total


def run_checks(base: str) -> str:
    """The full endpoint battery against `base`; returns the detected
    target kind ("replica" | "router")."""
    with _get(base, "/metrics") as r:
        ctype = r.headers.get("Content-Type")
        metrics_text = r.read().decode()
    if ctype != "text/plain; version=0.0.4":
        fail(f"/metrics content type {ctype!r}, want the Prometheus "
             "text exposition type")
    kind = (
        "router" if "oryx_router_build_info" in metrics_text
        else "replica"
    )
    prefixes = (
        ("oryx_router_", "oryx_anomaly_") if kind == "router"
        # oryx_pool_/oryx_page_ are the page-pool observatory's raw
        # families, oryx_device_time_/oryx_profile_ the device-time
        # attributor's, oryx_audit_/oryx_numerics_ the output-quality
        # observatory's — raw-named like oryx_anomaly_ because their
        # semantics are engine-independent.
        # oryx_cache_ is the prefix cache's host spill tier
        # (raw-named: tier semantics are engine-independent too).
        else ("oryx_serving_", "oryx_anomaly_", "oryx_pool_",
              "oryx_page_", "oryx_device_time_", "oryx_profile_",
              "oryx_audit_", "oryx_numerics_", "oryx_cache_")
    )
    info_family = (
        "oryx_router_build_info" if kind == "router"
        else "oryx_serving_build_info"
    )

    with _get(base, "/healthz") as r:
        if json.load(r) != {"status": "ok"}:
            fail("/healthz body is not {status: ok}")
    with _get(base, "/readyz") as r:
        ready = json.load(r)
        if r.status != 200 or ready.get("ready") is not True:
            fail(f"/readyz on a live target: want 200/true, "
                 f"got {r.status} {ready}")

    # Client-supplied request ids are honored END-TO-END (through the
    # router too): the response must echo the id, and it keys the
    # trace lookups below.
    rid = _completion(
        base, [{"role": "user", "content": "hello there"}],
        request_id="endpoint-check-1",
    )
    if rid != "endpoint-check-1":
        fail("client-supplied X-Request-Id was not honored "
             f"(sent endpoint-check-1, got {rid!r})")

    # Prefix/build_info checks run against the BOOT-time scrape (those
    # families exist before any traffic); the latency-histogram check
    # below re-scrapes after the burst for its samples.
    bad = [
        line for line in metrics_text.splitlines()
        if line and not line.startswith("#")
        and not line.startswith(prefixes)
    ]
    if bad:
        fail(f"unprefixed metric names for a {kind}: {bad[:5]}")
    if not re.search(
        rf'^{info_family}\{{[^}}]*engine="[^"]+"[^}}]*\}} 1$',
        metrics_text, re.M,
    ) or 'revision="' not in metrics_text:
        fail(f"{info_family} gauge with engine+revision labels "
             "missing from /metrics")
    if kind == "replica":
        if "oryx_serving_hbm_live_bytes" not in metrics_text:
            fail("device-memory gauge oryx_serving_hbm_live_bytes "
                 "missing from /metrics")
        # Output-quality & numerics families: pre-registered so the
        # ladders render (at zero) on an UNARMED default boot — the
        # dashboard row must exist before the first audit/probe.
        for verdict in ("pass", "drift", "fail"):
            if not re.search(
                rf'^oryx_audit_total\{{verdict="{verdict}"\}} ',
                metrics_text, re.M,
            ):
                fail(f"oryx_audit_total{{verdict=\"{verdict}\"}} not "
                     "pre-registered on an unarmed boot")
        for fam in (
            "oryx_audit_sampled_total",
            "oryx_audit_dropped_total",
            "oryx_audit_pending",
            "oryx_audit_replayed_tokens_total",
            "oryx_numerics_logits_finite_frac",
            "oryx_numerics_logits_absmax",
            "oryx_numerics_logits_rms",
            "oryx_numerics_logits_entropy",
            "oryx_numerics_logits_top1_margin",
            "oryx_numerics_samples_total",
        ):
            if not re.search(rf"^{fam} ", metrics_text, re.M):
                fail(f"{fam} not pre-registered on an unarmed boot")
        for fam in ("oryx_audit_logit_max_abs_diff", "oryx_audit_kl"):
            if not re.search(
                rf'^{fam}_bucket\{{le="\+Inf"\}} ', metrics_text, re.M
            ):
                fail(f"{fam} histogram ladder not pre-registered")
        # Host spill-tier families (prefix-cache host-RAM tier) and
        # the pool's wire-format label: pre-registered at zero so the
        # capacity dashboard renders before the first spill, and the
        # kv_dtype provenance is scrapeable from boot.
        for fam in (
            "oryx_cache_spilled_pages",
            "oryx_cache_host_bytes",
            "oryx_cache_reload_hit_total",
            "oryx_cache_reload_upload_total",
        ):
            if not re.search(rf"^{fam} ", metrics_text, re.M):
                fail(f"{fam} not pre-registered on boot")
        if not re.search(
            r'^oryx_pool_kv_dtype\{kv_dtype="(bf16|int8)"\} 1$',
            metrics_text, re.M,
        ):
            fail("oryx_pool_kv_dtype{kv_dtype=} build-info gauge "
                 "missing from /metrics")
    else:
        # The router has no HBM of its own; the fleet's shows through
        # the aggregation endpoint, every sample line replica-labeled.
        with _get(base, "/metrics/aggregate") as r:
            agg = r.read().decode()
        if not re.search(
            r'^oryx_serving_hbm_live_bytes\{[^}]*replica="[^"]+"',
            agg, re.M,
        ):
            fail("/metrics/aggregate missing replica-labeled "
                 "oryx_serving_hbm_live_bytes")
        unlabeled = [
            line for line in agg.splitlines()
            if line and not line.startswith("#")
            and line.startswith("oryx_serving_")
            and 'replica="' not in line
        ]
        if unlabeled:
            fail("aggregated replica samples missing the replica= "
                 f"label: {unlabeled[:5]}")

    with _get(base, "/debug/requests") as r:
        recorder = json.load(r)
    ids = [e.get("id") for e in recorder.get("requests", [])]
    if rid not in ids:
        fail(f"/debug/requests does not list request {rid} (got {ids})")

    with _get(base, f"/debug/trace?id={rid}") as r:
        tracejs = json.load(r)
    names = {e.get("name") for e in tracejs.get("traceEvents", [])}
    wanted = ["queue_wait", "prefill", "decode_chunk"]
    if kind == "router":
        # The acceptance bar for fleet tracing: ONE merged trace with
        # router spans AND the owning replica's engine spans, loadable
        # as Chrome trace JSON.
        wanted += ["route_decide", "upstream_ttfb"]
        if tracejs.get("merged") is not True:
            fail("/debug/trace through the router is not a merged "
                 f"trace (merged={tracejs.get('merged')!r})")
    for want in wanted:
        if want not in names:
            fail(f"/debug/trace missing span {want!r} (got "
                 f"{sorted(names)})")
    for ev in tracejs.get("traceEvents", []):
        if ev.get("ph") == "X" and not all(
            k in ev for k in ("name", "ts", "dur", "pid", "tid")
        ):
            fail(f"/debug/trace event not Chrome-trace shaped: {ev}")

    # Shared-prefix burst: several requests with one long system
    # prompt must light up the prefix-cache metric family (and, on a
    # router target, the affinity machinery keeps them on one
    # replica — asserted separately by --router-smoke).
    for i in range(3):
        _completion(base, [
            {"role": "system", "content": SYSMSG},
            {"role": "user", "content": f"question {i}?"},
        ], max_tokens=3)
    with _get(base, "/metrics") as r:
        metrics_text = r.read().decode()
    if kind == "router":
        with _get(base, "/metrics/aggregate") as r:
            cache_text = r.read().decode()
    else:
        cache_text = metrics_text
    for fam in (
        "oryx_serving_prefix_cache_hit_tokens_total",
        "oryx_serving_prefix_cache_miss_tokens_total",
        "oryx_serving_prefix_cache_evicted_pages_total",
        "oryx_serving_prefix_cache_entries",
        "oryx_serving_prefix_cache_pages",
        "oryx_serving_prefill_tokens_total",
    ):
        if not re.search(
            rf"^{fam}(?:\{{[^}}]*\}})? ([0-9.e+-]+)$", cache_text, re.M
        ):
            fail(f"prefix-cache metric {fam} missing or malformed "
                 "after the shared-prefix burst")
    if not re.search(
        r'^oryx_serving_prefill_chunk_tokens_bucket\{[^}]*le="\+Inf"[^}]*\} '
        r"[1-9]", cache_text, re.M,
    ):
        fail("prefill chunk-size histogram did not record any dispatch")
    hit = _labeled_total(
        cache_text, "oryx_serving_prefix_cache_hit_tokens_total"
    )
    if hit <= 0:
        fail("shared-prefix burst produced zero "
             "prefix_cache_hit_tokens_total — the cache never hit")

    # Latency quantiles through the SHARED bucket-interpolation
    # helpers (the loadgen report uses the same math): the histogram
    # must parse and produce finite, ordered quantiles. A replica's
    # own TTFT ladder, or the router's upstream-TTFB ladder.
    from oryx_tpu.utils.metrics import (
        REQUEST_COST_KEYS,
        histogram_quantile,
        parse_prom_histogram,
    )

    lat_family = (
        "oryx_router_upstream_ttfb_seconds" if kind == "router"
        else "oryx_serving_ttft_seconds"
    )
    hist = parse_prom_histogram(metrics_text, lat_family)
    if hist is None:
        fail(f"{lat_family} histogram missing")
    bounds, counts, total, _ = hist
    if total < 4:
        fail(f"{lat_family} recorded {total} < 4 requests")
    p50 = histogram_quantile(0.5, bounds, counts, total)
    p99 = histogram_quantile(0.99, bounds, counts, total)
    if not (0 < p50 <= p99):
        fail(f"{lat_family} quantiles malformed: p50={p50} p99={p99}")
    if kind == "replica" and not re.search(
        r"^oryx_serving_request_page_seconds_count [1-9]",
        metrics_text, re.M,
    ):
        fail("oryx_serving_request_page_seconds histogram did not "
             "record any finished request")

    # /debug/requests filters: ?limit= bounds the response,
    # ?state=done shows only finished requests — each carrying a
    # complete cost ledger — and a bogus state is a 400 (propagated
    # through the router's merge).
    with _get(base, "/debug/requests?limit=1") as r:
        lim = json.load(r)
    if len(lim["requests"]) != 1 or lim["returned"] != 1:
        fail(f"/debug/requests?limit=1 returned "
             f"{len(lim['requests'])} entries")
    if lim["total"] < 4:
        fail(f"/debug/requests?limit=1 total={lim['total']}, "
             "want >= 4 (the burst flowed through the recorder)")
    with _get(base, "/debug/requests?state=done") as r:
        done = json.load(r)
    if not done["requests"]:
        fail("/debug/requests?state=done is empty after the burst")
    for rec in done["requests"]:
        if not rec["done"]:
            fail(f"?state=done returned in-flight request {rec['id']}")
        cost = (rec.get("meta") or {}).get("cost")
        missing = [
            k for k in REQUEST_COST_KEYS
            if not isinstance(cost, dict) or k not in cost
        ]
        if missing:
            fail(f"finished request {rec['id']} cost ledger "
                 f"missing {missing}")
    try:
        with _get(base, "/debug/requests?state=bogus") as r:
            fail("/debug/requests?state=bogus did not 400")
    except urllib.error.HTTPError as e:
        if e.code != 400:
            fail(f"/debug/requests?state=bogus -> {e.code}, want 400")
        e.close()

    # Wide-event export: one JSONL line per terminal request, every
    # field drawn from the declared schema registry.
    from oryx_tpu.utils.metrics import REQUEST_EVENT_KEYS

    with _get(base, "/debug/requests?format=jsonl") as r:
        if r.headers.get("Content-Type") != "application/x-ndjson":
            fail("?format=jsonl content type is "
                 f"{r.headers.get('Content-Type')!r}")
        lines = [ln for ln in r.read().decode().splitlines() if ln]
    if len(lines) < 4:
        fail(f"?format=jsonl returned {len(lines)} events, want >= 4 "
             "(the burst reached terminal states)")
    from oryx_tpu.utils.metrics import OOM_EVENT_KEYS

    seen_ids = set()
    for ln in lines:
        try:
            ev = json.loads(ln)
        except ValueError:
            fail(f"?format=jsonl line is not JSON: {ln[:80]!r}")
        # The sink carries two declared schemas, dispatched on `kind`:
        # request events (no kind) and oom_pressure events.
        schema = (
            OOM_EVENT_KEYS if ev.get("kind") == "oom_pressure"
            else REQUEST_EVENT_KEYS
        )
        extra = set(ev) - set(schema)
        if extra:
            fail(f"wide event carries undeclared fields {sorted(extra)}")
        if ev.get("kind") == "oom_pressure":
            continue
        if not ev.get("request_id") or "status" not in ev:
            fail(f"wide event missing identity/outcome: {ev}")
        seen_ids.add(ev["request_id"])
    if rid not in seen_ids:
        fail(f"wide-event log does not contain request {rid}")

    # Step timeline: per-step records, and (replica) dispatch-kind
    # counts that reconcile EXACTLY with the dispatches_total counters
    # — both cumulative since boot, scraped with the engine quiesced.
    with _get(base, "/debug/timeline?n=16") as r:
        tl = json.load(r)
    if kind == "replica":
        if not tl.get("records"):
            fail("/debug/timeline returned no records after the burst")
        counts = tl.get("counts_by_kind") or {}
        if tl.get("total_steps") != sum(counts.values()):
            fail(f"timeline total_steps {tl.get('total_steps')} != "
                 f"sum of counts_by_kind {counts}")
        with _get(base, "/metrics") as r:
            mtext = r.read().decode()
        for k, v in counts.items():
            m = re.search(
                rf'^oryx_serving_dispatches_total\{{kind="{k}"\}} '
                rf"([0-9.e+-]+)$", mtext, re.M,
            )
            if not m or float(m.group(1)) != v:
                fail(f"timeline kind {k!r}={v} does not reconcile "
                     "with oryx_serving_dispatches_total "
                     f"({m.group(1) if m else 'absent'})")
    else:
        reps = tl.get("replicas") or {}
        if not reps:
            fail("router /debug/timeline returned no replicas")
        served = [
            r for r in reps.values()
            if isinstance(r.get("records"), list) and r["records"]
        ]
        if not served:
            fail(f"no replica timeline carries records: {tl}")

    # Page-pool observatory: on the quiesced target the ownership map
    # must reconcile exactly (free + slot + cache + shared == pool,
    # the allocator-invariant partition) and the summary must equal
    # the oryx_pool_* gauges from a scrape of the same quiesced state.
    with _get(base, "/debug/pages") as r:
        pm = json.load(r)
    if kind == "replica":
        s = pm.get("summary") or {}
        if not s.get("reconciled") or (
            s["free"] + s["slot"] + s["cache"] + s["shared"]
            != pm["num_pages"]
        ):
            fail(f"/debug/pages does not reconcile with the pool "
                 f"partition: {s}")
        if len(pm.get("pages") or []) != pm["num_pages"]:
            fail("/debug/pages is not one record per page "
                 f"({len(pm.get('pages') or [])} of {pm['num_pages']})")
        for rec in pm["pages"]:
            if rec["state"] not in ("free", "slot", "cache", "shared"):
                fail(f"unknown page state in the ownership map: {rec}")
            if (rec["state"] == "free") != (rec["refcount"] == 0):
                fail(f"page state/refcount mismatch: {rec}")
        with _get(base, "/metrics") as r:
            ptext = r.read().decode()
        for gname, key in (
            ("oryx_pool_free_pages", "free"),
            ("oryx_pool_slot_pages", "slot"),
            ("oryx_pool_cache_pages", "cache"),
            ("oryx_pool_shared_pages", "shared"),
            ("oryx_pool_size_pages", "num_pages"),
        ):
            m = re.search(rf"^{gname} ([0-9.e+-]+)$", ptext, re.M)
            want = s[key] if key != "num_pages" else pm["num_pages"]
            if not m or float(m.group(1)) != want:
                fail(f"{gname} ({m.group(1) if m else 'absent'}) does "
                     f"not equal the /debug/pages summary's {want}")
        if not re.search(
            r"^oryx_page_lifetime_seconds_count [1-9]", ptext, re.M
        ):
            fail("oryx_page_lifetime_seconds recorded no freed pages "
                 "after the burst (the free-time observer never fired)")
    else:
        reps = pm.get("replicas") or {}
        if not reps:
            fail("router /debug/pages returned no replicas")
        for rid, body in reps.items():
            if not (body.get("summary") or {}).get("reconciled"):
                fail(f"replica {rid} page map does not reconcile: "
                     f"{body}")
        # The forensic merge answers fleet-wide too (rings empty on a
        # healthy fleet).
        with _get(base, "/debug/oom") as r:
            om = json.load(r)
        if set(om.get("replicas") or {}) != set(reps):
            fail(f"router /debug/oom replicas {sorted(om)} do not "
                 f"match /debug/pages {sorted(reps)}")
    # Output-quality observatory surface: /debug/audit answers on an
    # UNARMED target (empty ring, zero verdicts that reconcile with the
    # zero counters); the router merges it per replica.
    with _get(base, "/debug/audit") as r:
        au = json.load(r)
    if kind == "replica":
        verdicts = au.get("verdicts") or {}
        if au.get("total") != sum(verdicts.values()):
            fail(f"/debug/audit total {au.get('total')} != sum of "
                 f"verdicts {verdicts}")
        with _get(base, "/metrics") as r:
            atext = r.read().decode()
        for verdict, want in verdicts.items():
            m = re.search(
                rf'^oryx_audit_total\{{verdict="{verdict}"\}} '
                rf"([0-9.e+-]+)$", atext, re.M,
            )
            if not m or float(m.group(1)) != want:
                fail(f"/debug/audit verdict {verdict!r}={want} does "
                     "not reconcile with oryx_audit_total "
                     f"({m.group(1) if m else 'absent'})")
    else:
        if not au.get("replicas"):
            fail("router /debug/audit returned no replicas")
    return kind


def _shutdown_replica(srv) -> None:
    if srv.scheduler is not None:
        srv.scheduler.close()
    srv.shutdown()


def run_oom_forensic_check() -> None:
    """Boot a fresh tiny replica with ONE injected page_alloc_oom
    armed (every=2,times=1: the second allocator call fails — by then
    the first streaming request is resident, so the capture names it)
    and assert the forensic contract: both requests still answer 200,
    exactly one /debug/oom record exists, its top-K is non-empty, the
    oom_pressure wide event rides the request log, and the post-
    incident page map reconciles."""
    import threading as threading_lib

    from oryx_tpu.serve import api_server
    from oryx_tpu.serve.pipeline import OryxInference
    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx as oryx_lib
    import jax

    cfg = cfg_lib.oryx_tiny()
    params = oryx_lib.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_Tokenizer(), params, cfg)
    srv = api_server.build_server(
        pipe, port=0, engine="continuous", num_slots=2, page_size=16,
        decode_chunk=4, max_ctx=512, prefill_chunk=32,
        faults_spec="page_alloc_oom:every=2,times=1",
    )
    threading_lib.Thread(target=srv.serve_forever, daemon=True).start()
    base = _base_of(srv)
    try:
        codes: list[int] = []

        def one(i: int, tokens: int) -> None:
            try:
                _completion(
                    base,
                    [{"role": "user",
                      "content": f"oom burst request {i} with a "
                      "longer prompt to prefill and decode"}],
                    max_tokens=tokens,
                )
                codes.append(200)
            except urllib.error.HTTPError as e:
                codes.append(e.code)
                e.close()

        threads = [
            threading.Thread(target=one, args=(i, t))
            for i, t in ((0, 64), (1, 8))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if codes != [200, 200]:
            fail(f"injected-OOM burst did not answer 200/200: {codes}")
        with _get(base, "/debug/oom?n=64") as r:
            oom = json.load(r)
        raised = [
            rec for rec in oom.get("records") or []
            if rec.get("trigger") == "oom"
        ]
        if len(raised) != 1:
            fail(f"injected page_alloc_oom produced {len(raised)} "
                 f"trigger=oom /debug/oom record(s), want exactly 1 "
                 f"(ring: {oom.get('total')})")
        rec = raised[0]
        if not rec.get("top_requests"):
            fail(f"forensic record has an empty top-K: {rec}")
        if not (rec.get("pool") or {}).get("reconciled"):
            fail(f"forensic record captured an unreconciled pool: "
                 f"{rec.get('pool')}")
        with _get(base, "/debug/requests?format=jsonl") as r:
            events = [json.loads(ln) for ln in
                      r.read().decode().splitlines() if ln]
        ooms = [e for e in events if e.get("kind") == "oom_pressure"
                and e.get("trigger") == "oom"]
        if len(ooms) != 1 \
                or ooms[0].get("forensic_index") != rec.get("index"):
            fail(f"expected one trigger=oom wide event joined to "
                 f"forensic #{rec.get('index')}, got {ooms}")
        with _get(base, "/debug/pages?format=summary") as r:
            s = json.load(r)["summary"]
        if not s.get("reconciled") or s.get("slot") != 0:
            fail(f"post-incident /debug/pages does not reconcile: {s}")
        print("oom forensic check OK: 200/200 under one injected "
              "OOM, 1 forensic record (non-empty top-K), wide event "
              "joined, pool reconciled")
    finally:
        from oryx_tpu.utils import faults

        faults.reset()
        _shutdown_replica(srv)


def run_audit_check() -> None:
    """The output-quality observatory gate (ISSUE 14): the SAME
    sequential greedy burst against an ARMED (--audit-sample-every 1)
    and an UNARMED tiny replica, gating:

      * every sampled request audits verdict=pass on the fp path —
        zero fail, zero drift;
      * the /debug/audit ring/verdict counts reconcile EXACTLY with
        oryx_audit_total{verdict=};
      * every kind="audit" wide event validates against the declared
        schema (utils.metrics.AUDIT_EVENT_KEYS) and joins the ring by
        audit_index;
      * the auditor observes, never perturbs: live-traffic reply bytes
        AND oryx_serving_dispatches_total{kind=} are identical between
        the armed and unarmed runs (sequential requests — the dispatch
        schedule is deterministic).
    """
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx as oryx_lib
    from oryx_tpu.serve import api_server
    from oryx_tpu.serve.pipeline import OryxInference
    from oryx_tpu.utils.metrics import AUDIT_EVENT_KEYS

    cfg = cfg_lib.oryx_tiny()
    params = oryx_lib.init_params(cfg, jax.random.key(0))

    bursts = [
        ("hello there, audit me", 6),
        ("a different question now", 4),
        ("hello there, audit me", 6),  # repeat: splice path audited too
        ("one more to finish the burst", 5),
    ]

    def boot(audit_every: int):
        pipe = OryxInference(_Tokenizer(), params, cfg)
        srv = api_server.build_server(
            pipe, port=0, engine="continuous", num_slots=2,
            page_size=16, decode_chunk=4, max_ctx=512, prefill_chunk=32,
            audit_sample_every=audit_every,
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def drive(srv) -> tuple[list[str], dict[str, float]]:
        base = _base_of(srv)
        replies = []
        for i, (q, toks) in enumerate(bursts):
            req = urllib.request.Request(
                base + "/v1/chat/completions",
                data=json.dumps({
                    "messages": [{"role": "user", "content": q}],
                    "max_tokens": toks,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as r:
                body = json.load(r)
            replies.append(body["choices"][0]["message"]["content"])
        with _get(base, "/metrics") as r:
            text = r.read().decode()
        dispatches = {
            m.group(1): float(m.group(2))
            for m in re.finditer(
                r'^oryx_serving_dispatches_total\{kind="([^"]+)"\} '
                r"([0-9.e+-]+)$", text, re.M,
            )
        }
        return replies, dispatches

    armed = boot(1)
    plain = boot(0)
    try:
        armed_replies, armed_disp = drive(armed)
        base = _base_of(armed)
        # Drain the audit backlog: replays run at engine idle points,
        # so after the last reply they complete within a poll window.
        import time as time_lib

        deadline = time_lib.monotonic() + 120
        while time_lib.monotonic() < deadline:
            with _get(base, "/debug/audit?n=64") as r:
                au = json.load(r)
            if au.get("pending") == 0 and au.get("total", 0) >= len(
                bursts
            ):
                break
            time_lib.sleep(0.1)
        if au.get("pending") != 0:
            fail(f"audit backlog never drained: {au.get('pending')} "
                 "pending after the burst")
        verdicts = au.get("verdicts") or {}
        if verdicts.get("fail") or verdicts.get("drift"):
            fail(f"non-pass audit verdict(s) on the fp path: "
                 f"{verdicts} (records: {au.get('records')})")
        if au.get("total") != len(bursts) \
                or verdicts.get("pass") != len(bursts):
            fail(f"expected {len(bursts)} pass audits, got total="
                 f"{au.get('total')} verdicts={verdicts}")
        # Ring <-> counter reconciliation on the quiesced replica.
        with _get(base, "/metrics") as r:
            atext = r.read().decode()
        for verdict, want in verdicts.items():
            m = re.search(
                rf'^oryx_audit_total\{{verdict="{verdict}"\}} '
                rf"([0-9.e+-]+)$", atext, re.M,
            )
            if not m or float(m.group(1)) != want:
                fail(f"oryx_audit_total verdict {verdict!r} "
                     f"({m.group(1) if m else 'absent'}) does not "
                     f"reconcile with /debug/audit's {want}")
        if not re.search(
            r"^oryx_audit_logit_max_abs_diff_count [1-9]", atext, re.M
        ):
            fail("oryx_audit_logit_max_abs_diff recorded no samples "
                 "over an armed burst")
        # Every audit's wide event validates and joins the ring.
        with _get(base, "/debug/requests?format=jsonl") as r:
            events = [json.loads(ln) for ln in
                      r.read().decode().splitlines() if ln]
        audits = [e for e in events if e.get("kind") == "audit"]
        if len(audits) != len(bursts):
            fail(f"{len(audits)} kind=audit wide event(s), want "
                 f"{len(bursts)}")
        indices = {rec["index"] for rec in au.get("records") or []}
        for ev in audits:
            extra = set(ev) - set(AUDIT_EVENT_KEYS)
            if extra:
                fail(f"audit wide event carries undeclared fields "
                     f"{sorted(extra)}")
            if ev.get("verdict") != "pass":
                fail(f"audit wide event is not a pass: {ev}")
            if ev.get("audit_index") not in indices:
                fail(f"audit wide event index {ev.get('audit_index')} "
                     "does not join the /debug/audit ring")
        # Never-perturb A/B: byte parity + identical dispatch schedule
        # against the unarmed twin.
        plain_replies, plain_disp = drive(plain)
        if armed_replies != plain_replies:
            fail("armed vs unarmed replies diverged — the auditor "
                 f"perturbed live traffic: {armed_replies} vs "
                 f"{plain_replies}")
        if armed_disp != plain_disp:
            fail("armed vs unarmed dispatch counters diverged — the "
                 f"auditor perturbed the engine: {armed_disp} vs "
                 f"{plain_disp}")
        print(f"audit smoke OK: {len(bursts)}/{len(bursts)} audits "
              "pass, ring==counters, wide events schema-valid and "
              "joined, armed==unarmed byte parity and dispatch "
              f"schedule ({armed_disp})")
    finally:
        _shutdown_replica(armed)
        _shutdown_replica(plain)


def run_journal_check() -> None:
    """The engine flight-recorder gate (ISSUE 18): the SAME sequential
    greedy burst against a --journal ARMED and an unarmed tiny
    replica, gating:

      * /debug/journal is well-formed and reconciled: armed=true, the
        sealed header carries the scheduler geometry, counts_by_kind
        sums to total, one submit and one finish entry per request
        (the unarmed twin answers the same shape with armed=false);
      * the journal FILE replays offline byte-exactly
        (scripts/replay_journal.py as a library): first_divergence is
        None over the replayed decision stream, every finish entry's
        reply/token fingerprints match, and the deterministic cost
        ledgers are equal — the capture -> replay contract of
        docs/OBSERVABILITY.md "Incident replay";
      * the journal observes, never perturbs: live-traffic reply
        bytes AND oryx_serving_dispatches_total{kind=} are identical
        between the armed and unarmed runs.
    """
    import tempfile

    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx as oryx_lib
    from oryx_tpu.serve import api_server
    from oryx_tpu.serve import journal as journal_lib
    from oryx_tpu.serve.pipeline import OryxInference

    import replay_journal as rj

    cfg = cfg_lib.oryx_tiny()
    params = oryx_lib.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_Tokenizer(), params, cfg)
    jpath = os.path.join(tempfile.mkdtemp(), "journal.jsonl")

    bursts = [
        ("hello there, journal me", 6),
        ("a different question now", 4),
        ("hello there, journal me", 6),  # repeat: splice path journaled
        ("one more to finish the burst", 5),
    ]

    def boot(path):
        srv = api_server.build_server(
            pipe, port=0, engine="continuous", num_slots=2,
            page_size=16, decode_chunk=4, max_ctx=512, prefill_chunk=32,
            journal_path=path,
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def drive(srv) -> tuple[list[str], dict[str, float]]:
        base = _base_of(srv)
        replies = []
        for q, toks in bursts:
            req = urllib.request.Request(
                base + "/v1/chat/completions",
                data=json.dumps({
                    "messages": [{"role": "user", "content": q}],
                    "max_tokens": toks,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as r:
                body = json.load(r)
            replies.append(body["choices"][0]["message"]["content"])
        with _get(base, "/metrics") as r:
            text = r.read().decode()
        dispatches = {
            m.group(1): float(m.group(2))
            for m in re.finditer(
                r'^oryx_serving_dispatches_total\{kind="([^"]+)"\} '
                r"([0-9.e+-]+)$", text, re.M,
            )
        }
        return replies, dispatches

    armed = boot(jpath)
    plain = boot(None)
    try:
        armed_replies, armed_disp = drive(armed)
        with _get(_base_of(armed), "/debug/journal?n=512") as r:
            ring = json.load(r)
        if not ring.get("armed") or ring.get("path") != jpath:
            fail(f"/debug/journal on the armed replica is not armed "
                 f"at {jpath}: {ring.get('armed')}/{ring.get('path')}")
        counts = ring.get("counts_by_kind") or {}
        if sum(counts.values()) != ring.get("total"):
            fail(f"/debug/journal counts_by_kind {counts} does not "
                 f"sum to total {ring.get('total')}")
        if counts.get("submit") != len(bursts) \
                or counts.get("finish") != len(bursts):
            fail(f"expected {len(bursts)} submit and finish entries, "
                 f"got {counts}")
        hdr_cfg = (ring.get("header") or {}).get("config") or {}
        for key in ("num_slots", "page_size", "seed"):
            if key not in hdr_cfg:
                fail(f"journal header config is missing {key!r}: "
                     f"{sorted(hdr_cfg)}")
        with _get(_base_of(plain), "/debug/journal") as r:
            off = json.load(r)
        if off.get("armed") or off.get("total") or off.get("entries"):
            fail(f"unarmed replica's /debug/journal is not the "
                 f"disarmed shape: {off}")
        # Quiesce the armed engine (close() joins the thread and
        # detaches the journal's fault observer; the sink flushed
        # every line already), then replay the FILE offline.
        armed.scheduler.close()
        header, entries = journal_lib.read_journal(jpath)
        res = rj.run_replay(header, entries, pipe=pipe)
        if res["feed_errors"] or res["timed_out"] or res["gave_up"]:
            fail(f"offline replay did not run clean: "
                 f"feed_errors={res['feed_errors']} "
                 f"timed_out={res['timed_out']} gave_up={res['gave_up']}")
        div = rj.first_divergence(entries, res["entries"])
        if div is not None:
            fail(f"offline replay diverged from the live journal: "
                 f"{div}")
        matched, total_fp, bad = rj.reply_match(entries, res["entries"])
        if matched != total_fp or total_fp != len(bursts):
            fail(f"replayed reply fingerprints: {matched}/{total_fp} "
                 f"matched (want {len(bursts)}/{len(bursts)}; "
                 f"divergent ids {bad})")
        # Never-perturb A/B against the unarmed twin.
        plain_replies, plain_disp = drive(plain)
        if armed_replies != plain_replies:
            fail("armed vs unarmed replies diverged — the journal "
                 f"perturbed live traffic: {armed_replies} vs "
                 f"{plain_replies}")
        if armed_disp != plain_disp:
            fail("armed vs unarmed dispatch counters diverged — the "
                 f"journal perturbed the engine: {armed_disp} vs "
                 f"{plain_disp}")
        print(f"journal smoke OK: {len(bursts)} requests journaled "
              f"({sum(counts.values())} entries), offline replay "
              f"byte-identical ({matched}/{total_fp} replies, "
              "decision-for-decision equal), armed==unarmed byte "
              f"parity and dispatch schedule ({armed_disp})")
    finally:
        _shutdown_replica(armed)
        _shutdown_replica(plain)


def run_router_smoke() -> None:
    """Two tiny replicas + a router: the full gate against the ROUTER,
    then the affinity assertion — the shared-prefix burst must
    concentrate on one replica (its prefix_cache_hit_tokens_total
    dominates the fleet total)."""
    from oryx_tpu.serve.router import build_router

    reps = [boot_tiny_server(replica_id=f"r{i}") for i in range(2)]
    rsrv = build_router(
        [(f"r{i}", _base_of(s)) for i, s in enumerate(reps)],
        port=0, poll_s=0.1,
    )
    threading.Thread(target=rsrv.serve_forever, daemon=True).start()
    try:
        kind = run_checks(_base_of(rsrv))
        if kind != "router":
            fail(f"router smoke detected target kind {kind!r}")
        hits = []
        for i, s in enumerate(reps):
            with _get(_base_of(s), "/metrics") as r:
                text = r.read().decode()
            m = re.search(
                r"^oryx_serving_prefix_cache_hit_tokens_total "
                r"([0-9.e+-]+)$", text, re.M,
            )
            hits.append(float(m.group(1)) if m else 0.0)
        total = sum(hits)
        if total <= 0:
            fail("router smoke: no prefix-cache hits anywhere — "
                 f"affinity routed nothing usefully (hits={hits})")
        if max(hits) < 0.8 * total:
            fail("router smoke: shared-prefix burst did not "
                 f"concentrate on one replica (hit tokens {hits}; "
                 "want one replica >= 80% of the total)")
        with _get(_base_of(rsrv), "/metrics") as r:
            rt = r.read().decode()
        m = re.search(
            r"^oryx_router_affinity_hit_rate ([0-9.e+-]+)$", rt, re.M
        )
        if not m or float(m.group(1)) <= 0:
            fail("oryx_router_affinity_hit_rate did not move")
        print(f"router smoke OK: hit tokens per replica {hits}, "
              f"affinity_hit_rate={m.group(1)}")
    finally:
        rsrv.stop_prober()  # before the replicas go: no eject noise
        for s in reps:
            _shutdown_replica(s)
        rsrv.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser(
        description="serving endpoint well-formedness gate "
        "(see module docstring)"
    )
    ap.add_argument(
        "--base-url", default=None,
        help="live target (replica or router); omitted = boot a tiny "
        "CPU replica in-process",
    )
    ap.add_argument(
        "--router-smoke", action="store_true",
        help="boot 2 tiny replicas + a router, run the gate against "
        "the router, and assert shared-prefix affinity dominance",
    )
    ap.add_argument(
        "--audit-smoke", action="store_true",
        help="boot an --audit-sample-every 1 replica and an unarmed "
        "twin, run the same sequential burst against both, and gate "
        "all-pass verdicts, ring<->counter reconciliation, audit "
        "wide-event schema, and armed==unarmed byte parity + "
        "dispatch schedule (the auditor observes, never perturbs)",
    )
    ap.add_argument(
        "--journal-smoke", action="store_true",
        help="boot a --journal armed replica and an unarmed twin, run "
        "the same sequential burst against both, replay the journal "
        "file offline byte-exactly (scripts/replay_journal.py), and "
        "gate armed==unarmed byte parity + dispatch schedule (the "
        "journal observes, never perturbs)",
    )
    args = ap.parse_args()
    if args.journal_smoke:
        if args.base_url:
            ap.error("--journal-smoke self-boots; drop --base-url")
        run_journal_check()
        return
    if args.router_smoke:
        if args.base_url:
            ap.error("--router-smoke self-boots; drop --base-url")
        run_router_smoke()
        return
    if args.audit_smoke:
        if args.base_url:
            ap.error("--audit-smoke self-boots; drop --base-url")
        run_audit_check()
        return

    srv = None
    base = args.base_url
    try:
        if base is None:
            srv = boot_tiny_server()
            base = _base_of(srv)
        kind = run_checks(base)
    finally:
        if srv is not None:
            _shutdown_replica(srv)
    if args.base_url is None:
        # Self-boot only (the fault registry is process-global and the
        # scenario needs its own deterministic injection schedule).
        run_oom_forensic_check()
    print(f"serving endpoints OK ({kind}): /healthz + /readyz + "
          "/metrics (content-type, prefix, build_info"
          + (", aggregate replica labels" if kind == "router"
             else ", hbm gauges")
          + ") + /debug/requests (+ limit/state filters, cost ledger, "
          "wide-event jsonl) + /debug/trace"
          + (" (merged router+replica)" if kind == "router" else "")
          + " + /debug/timeline (dispatch-kind reconciliation) + "
          "/debug/pages (ownership-map reconciliation vs the "
          "oryx_pool_* gauges) + "
          "honored X-Request-Id + prefix-cache family under a "
          "shared-prefix burst + latency quantiles via the shared "
          "histogram helper")


if __name__ == "__main__":
    main()
