"""CI well-formedness gate for the serving observability surface.

Boots a short-lived CPU server (tiny geometry, continuous engine),
pushes one request through it, then checks:

  * GET /healthz — 200 liveness;
  * GET /readyz — 200 with ready:true while the scheduler loop is
    alive (the load-balancer probe that replaces spending a real
    completion);
  * GET /metrics — exact Prometheus content type
    (`text/plain; version=0.0.4`), every metric name carries the
    `oryx_serving_` prefix (an unprefixed name would collide in any
    shared Prometheus; the cross-source `oryx_anomaly_` family is the
    one deliberate exception), the build_info gauge is present with
    revision + engine labels, and the HBM gauges exist;
  * GET /debug/requests — valid JSON, the request we sent is recorded;
    ?limit= bounds the response, ?state=done returns only finished
    requests and every one carries a COMPLETE per-request cost ledger
    (utils/metrics.REQUEST_COST_KEYS), a bogus state is a 400;
  * GET /debug/trace?id= — valid Chrome trace JSON with a non-empty
    traceEvents list covering prefill and decode;
  * the TTFT histogram read back through the SHARED quantile helpers
    (utils/metrics.parse_prom_histogram + histogram_quantile — the
    same math scripts/loadgen.py reports with): finite, positive,
    ordered p50 <= p99;
  * prefix cache under a shared-prefix burst — after several requests
    carrying one long system prompt, the
    `oryx_serving_prefix_cache_{hit,miss}_tokens_total` counters,
    entries/pages gauges, eviction counter and the
    `oryx_serving_prefill_chunk_tokens` histogram are present and
    well-formed, and hit_tokens actually moved (the burst shared).

Exit 0 = all good; nonzero prints what broke. Wired into
scripts/check_tier1.sh after the pytest gate.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""


class _Tokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def main() -> None:
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve import api_server
    from oryx_tpu.serve.pipeline import OryxInference

    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_Tokenizer(), params, cfg)
    srv = api_server.build_server(
        pipe, port=0, engine="continuous", num_slots=2, page_size=16,
        decode_chunk=4, max_ctx=512, prefill_chunk=32,
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            if json.load(r) != {"status": "ok"}:
                fail("/healthz body is not {status: ok}")
        with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
            ready = json.load(r)
            if r.status != 200 or ready.get("ready") is not True:
                fail(f"/readyz with a live scheduler: want 200/true, "
                     f"got {r.status} {ready}")

        req = urllib.request.Request(
            base + "/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "hello there"}],
                "max_tokens": 4,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            rid = r.headers.get("X-Request-Id")
            json.load(r)
        if not rid:
            fail("completion response missing X-Request-Id header")

        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            ctype = r.headers.get("Content-Type")
            metrics_text = r.read().decode()
        if ctype != "text/plain; version=0.0.4":
            fail(f"/metrics content type {ctype!r}, want the Prometheus "
                 "text exposition type")
        bad = [
            line for line in metrics_text.splitlines()
            if line and not line.startswith("#")
            and not line.startswith(("oryx_serving_", "oryx_anomaly_"))
        ]
        if bad:
            fail(f"unprefixed metric names: {bad[:5]}")
        if "oryx_serving_hbm_live_bytes" not in metrics_text:
            fail("device-memory gauge oryx_serving_hbm_live_bytes "
                 "missing from /metrics")
        if not re.search(
            r'^oryx_serving_build_info\{[^}]*engine="[^"]+"[^}]*\} 1$',
            metrics_text, re.M,
        ) or 'revision="' not in metrics_text:
            fail("oryx_serving_build_info gauge with engine+revision "
                 "labels missing from /metrics")

        with urllib.request.urlopen(
            base + "/debug/requests", timeout=30
        ) as r:
            recorder = json.load(r)
        ids = [e.get("id") for e in recorder.get("requests", [])]
        if rid not in ids:
            fail(f"/debug/requests does not list request {rid} "
                 f"(got {ids})")

        with urllib.request.urlopen(
            base + f"/debug/trace?id={rid}", timeout=30
        ) as r:
            tracejs = json.load(r)
        names = {
            e.get("name") for e in tracejs.get("traceEvents", [])
        }
        for want in ("queue_wait", "prefill", "decode_chunk"):
            if want not in names:
                fail(f"/debug/trace missing span {want!r} (got "
                     f"{sorted(names)})")

        # Shared-prefix burst: several requests with one long system
        # prompt must light up the prefix-cache metric family.
        sysmsg = ("You are a careful assistant. Study the context and "
                  "answer briefly. " * 2)
        for i in range(3):
            burst = urllib.request.Request(
                base + "/v1/chat/completions",
                data=json.dumps({
                    "messages": [
                        {"role": "system", "content": sysmsg},
                        {"role": "user", "content": f"question {i}?"},
                    ],
                    "max_tokens": 3,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(burst, timeout=300) as r:
                json.load(r)
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            metrics_text = r.read().decode()
        for fam in (
            "oryx_serving_prefix_cache_hit_tokens_total",
            "oryx_serving_prefix_cache_miss_tokens_total",
            "oryx_serving_prefix_cache_evicted_pages_total",
            "oryx_serving_prefix_cache_entries",
            "oryx_serving_prefix_cache_pages",
            "oryx_serving_prefill_tokens_total",
        ):
            m = re.search(
                rf"^{fam} ([0-9.e+-]+)$", metrics_text, re.M
            )
            if not m:
                fail(f"prefix-cache metric {fam} missing or malformed "
                     "after the shared-prefix burst")
        if not re.search(
            r'^oryx_serving_prefill_chunk_tokens_bucket\{le="\+Inf"\} '
            r"[1-9]", metrics_text, re.M,
        ):
            fail("prefill chunk-size histogram did not record any "
                 "dispatch")
        hit = float(re.search(
            r"^oryx_serving_prefix_cache_hit_tokens_total ([0-9.e+-]+)$",
            metrics_text, re.M,
        ).group(1))
        if hit <= 0:
            fail("shared-prefix burst produced zero "
                 "prefix_cache_hit_tokens_total — the cache never hit")

        # TTFT quantiles through the SHARED bucket-interpolation
        # helpers (the loadgen report uses the same math): the
        # histogram must parse and produce finite, ordered quantiles.
        from oryx_tpu.utils.metrics import (
            REQUEST_COST_KEYS,
            histogram_quantile,
            parse_prom_histogram,
        )

        hist = parse_prom_histogram(
            metrics_text, "oryx_serving_ttft_seconds"
        )
        if hist is None:
            fail("oryx_serving_ttft_seconds histogram missing")
        bounds, counts, total, _ = hist
        if total < 4:
            fail(f"ttft histogram recorded {total} < 4 requests")
        p50 = histogram_quantile(0.5, bounds, counts, total)
        p99 = histogram_quantile(0.99, bounds, counts, total)
        if not (0 < p50 <= p99):
            fail(f"ttft quantiles malformed: p50={p50} p99={p99}")
        # The per-request cost-ledger families must render (at the
        # request count) alongside the latency ladders.
        if not re.search(
            r"^oryx_serving_request_page_seconds_count [1-9]",
            metrics_text, re.M,
        ):
            fail("oryx_serving_request_page_seconds histogram did not "
                 "record any finished request")

        # /debug/requests filters: ?limit= bounds the response,
        # ?state=done shows only finished requests — each carrying a
        # complete cost ledger — and a bogus state is a 400.
        with urllib.request.urlopen(
            base + "/debug/requests?limit=1", timeout=30
        ) as r:
            lim = json.load(r)
        if len(lim["requests"]) != 1 or lim["returned"] != 1:
            fail(f"/debug/requests?limit=1 returned "
                 f"{len(lim['requests'])} entries")
        if lim["total"] < 4:
            fail(f"/debug/requests?limit=1 total={lim['total']}, "
                 "want >= 4 (the burst flowed through the recorder)")
        with urllib.request.urlopen(
            base + "/debug/requests?state=done", timeout=30
        ) as r:
            done = json.load(r)
        if not done["requests"]:
            fail("/debug/requests?state=done is empty after the burst")
        for rec in done["requests"]:
            if not rec["done"]:
                fail(f"?state=done returned in-flight request "
                     f"{rec['id']}")
            cost = (rec.get("meta") or {}).get("cost")
            missing = [
                k for k in REQUEST_COST_KEYS
                if not isinstance(cost, dict) or k not in cost
            ]
            if missing:
                fail(f"finished request {rec['id']} cost ledger "
                     f"missing {missing}")
        try:
            with urllib.request.urlopen(
                base + "/debug/requests?state=bogus", timeout=30
            ) as r:
                fail("/debug/requests?state=bogus did not 400")
        except urllib.error.HTTPError as e:
            if e.code != 400:
                fail(f"/debug/requests?state=bogus -> {e.code}, "
                     "want 400")
            e.close()
    finally:
        if srv.scheduler is not None:
            srv.scheduler.close()
        srv.shutdown()
    print("serving endpoints OK: /healthz + /readyz + /metrics "
          "(content-type, prefix, build_info, hbm gauges) + "
          "/debug/requests (+ limit/state filters, cost ledger) + "
          "/debug/trace + prefix-cache family under a shared-prefix "
          "burst + ttft quantiles via the shared histogram helper")


if __name__ == "__main__":
    main()
