#!/usr/bin/env python3
"""Offline incident replay for the engine decision journal.

Reads a journal captured with ``--journal PATH`` (the flight recorder,
oryx_tpu/serve/journal.py), rebuilds a COLD scheduler from the header's
flags/seed/pool geometry, feeds the journaled admission stream at its
recorded step gates, and asserts the incident reproduces bit-for-bit:

  * byte-identical reply tokens per request (the finish entries'
    reply/token fingerprints),
  * decision-for-decision stream equality over REPLAYED_KINDS
    (admit/splice/evict/step/fault/restart/finish),
  * cost-ledger equality (the DETERMINISTIC_COST_KEYS subset).

On mismatch it prints a first-divergence report — seq, decision kind,
the first differing field, both values — and exits 2. By contract
(docs/OBSERVABILITY.md "Incident replay") submit arrival, admission-
control rejects and degraded transitions are timing-coupled and NOT
compared; live cancellations and deadline expiries are likewise
load-coupled and will legitimately diverge.

What-if mode: ``--override k=v,...`` replays the IDENTICAL workload
under altered flags (kv_dtype, prefill_chunk, speculate,
host_cache_bytes, ...) and emits a bench_compare-style cost/goodput
diff table instead of asserting equality — a counterfactual ("would
int8 KV have avoided the eviction storm?") from one captured window.

Usage::

    python scripts/replay_journal.py /tmp/journal.jsonl
    python scripts/replay_journal.py /tmp/journal.jsonl \
        --override kv_dtype=int8,prefill_chunk=16 --out whatif.json

The default pipeline is the tiny self-test model every smoke harness
uses (oryx_tiny + the ord tokenizer — chaos_suite, loadgen, the test
suite); pass --model-path/--shard to replay a journal captured against
a real checkpoint. The pipeline must match the capturing server or the
reply fingerprints cannot reproduce.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import deque
from pathlib import Path
from typing import Any

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))
sys.path.insert(0, str(ROOT / "scripts"))

import bench_compare  # noqa: E402
from oryx_tpu.serve import journal as journal_lib  # noqa: E402
from oryx_tpu.utils import faults  # noqa: E402

# Per-entry fields excluded from the decision-for-decision comparison:
# `seq` is a global counter shared with the non-replayed kinds (submit/
# reject/degraded interleave differently by contract) and `ts_unix_s`
# is wall clock.
VOLATILE_FIELDS = ("seq", "ts_unix_s")

# Header-config keys that are ContinuousScheduler constructor kwargs,
# in constructor spelling — the cold-rebuild set, and (plus faults_spec)
# the --override whitelist.
GEOMETRY_KEYS = (
    "num_slots", "page_size", "chunk", "max_ctx", "num_pages", "seed",
    "prefill_chunk", "prefix_cache", "ragged", "speculate", "kv_dtype",
    "host_cache_bytes", "degraded_clamp_tokens", "fuse_steps",
)
OVERRIDE_KEYS = GEOMETRY_KEYS + ("faults_spec",)

WHATIF_SCHEMA = 1
WHATIF_ROW_KEYS = (
    "series", "baseline", "current", "direction", "rel_tol", "verdict",
    "note",
)


class _CharTokenizer:
    """Byte-compatible with chaos_suite._Tokenizer / loadgen
    ._CharTokenizer / the test suite's FakeTokenizer: replaying a
    journal captured by any of them reproduces the exact token ids."""

    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


def build_tiny_pipe():
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve.pipeline import OryxInference

    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    return OryxInference(_CharTokenizer(), params, cfg)


# ---------------------------------------------------------------------------
# Workload plan
# ---------------------------------------------------------------------------


def plan_feed(entries: list[dict[str, Any]]
              ) -> tuple[list[dict[str, Any]], list[tuple[str, str]]]:
    """The feed plan: one item per replayable submit, in arrival order.

    Each item carries the journaled payload/sampling/streaming, the
    EFFECTIVE max_new (the first admit entry's budget — the degraded
    clamp applied at the live queue head — falling back to the
    requested value), and `feed_step`: the engine step at which the
    live run first admitted it (validation rejects never admit; they
    gate on their finish step). Returns (plan, skipped) where skipped
    lists (request_id, reason) for submits replay cannot carry.
    """
    rejected = {
        e.get("request_id") for e in entries if e["kind"] == "reject"
    }
    first_admit: dict[str, dict[str, Any]] = {}
    finish_step: dict[str, int] = {}
    for e in entries:
        rid = e.get("request_id")
        if e["kind"] == "admit" and rid not in first_admit:
            first_admit[rid] = e
        elif e["kind"] == "finish" and rid not in finish_step:
            finish_step[rid] = int(e.get("step") or 0)
    submits = sorted(
        (e for e in entries if e["kind"] == "submit"),
        key=lambda e: e["arrival_seq"],
    )
    plan: list[dict[str, Any]] = []
    skipped: list[tuple[str, str]] = []
    for e in submits:
        rid = e["request_id"]
        if rid in rejected:
            skipped.append(
                (rid, "admission-control reject (timing-coupled, "
                      "excluded by contract)")
            )
            continue
        if e.get("prompt") is None:
            # Non-JSON payloads journal a fingerprint only (see
            # _journal_submit): the workload cannot be rebuilt.
            raise ValueError(
                f"request {rid} journaled a prompt fingerprint, not a "
                "payload (programmatic non-JSON submit): this journal "
                "is not replayable"
            )
        admit = first_admit.get(rid)
        if admit is None and rid not in finish_step:
            skipped.append(
                (rid, "no admit or finish entry (capture ended "
                      "mid-flight or the journal rotated past it)")
            )
            continue
        plan.append({
            "request_id": rid,
            "prompt": e["prompt"],
            "sampling": e.get("sampling") or {},
            "max_new": int(
                admit["max_new"] if admit is not None else e["max_new"]
            ),
            "streaming": bool(e.get("streaming")),
            "feed_step": int(
                admit["step"] if admit is not None else finish_step[rid]
            ),
        })
    return plan, skipped


# ---------------------------------------------------------------------------
# Replay run
# ---------------------------------------------------------------------------


def run_replay(header: dict[str, Any], entries: list[dict[str, Any]], *,
               pipe=None, overrides: dict[str, Any] | None = None,
               timeout_s: float = 300.0) -> dict[str, Any]:
    """Cold-rebuild the scheduler the header describes (plus override
    deltas), replay the journaled admission stream, and return
    {"entries": replay journal entries, "skipped", "feed_errors",
    "timed_out", "gave_up"}.

    The feeder runs on the engine thread at the top of every loop
    iteration (scheduler.replay_feeder): it submits pending requests
    once `steps_run` reaches their recorded gate — or, under overrides
    that finish the resident work in fewer steps, once the engine is
    fully idle (the anti-hang fallback; in faithful replay an idle
    engine has by construction already reached the next gate, because
    the step clock only advances on dispatches).
    """
    from oryx_tpu.serve.api_server import EngineSupervisor
    from oryx_tpu.serve.scheduler import ContinuousScheduler

    cfg = dict(header.get("config") or {})
    if overrides:
        cfg.update(overrides)
    plan, skipped = plan_feed(entries)
    if pipe is None:
        pipe = build_tiny_pipe()
    kw = {k: cfg[k] for k in GEOMETRY_KEYS if k in cfg}
    # The draft model is part of the recorded machine: its source spec
    # (an init:V:D:W:SEED string or a checkpoint path) is stamped in the
    # header, and device-side speculation replays bit-for-bit only with
    # the same weights.
    drafter = None
    if cfg.get("draft_model"):
        from oryx_tpu.models import generate as generate_lib

        drafter = generate_lib.NeuralDrafter.from_spec(cfg["draft_model"])
        kw["drafter"] = drafter
    journal = journal_lib.DecisionJournal(
        None, keep=max(4096, 4 * len(entries) + 8 * len(plan)),
    )
    # The seeded fault schedule is part of the recorded configuration:
    # arm it before construction so hit counts start from zero exactly
    # as the live process's did.
    faults.configure(cfg.get("faults_spec") or None)
    sched = ContinuousScheduler(
        pipe, autostart=False, journal=journal,
        engine_label=str(cfg.get("engine") or "continuous"),
        replica_id=cfg.get("replica"),
        # No max_queue / timeouts / SLO watchers: admission control,
        # deadlines and the degraded ladder are timing-coupled and
        # excluded from replay by contract.
        **kw,
    )

    pending = deque(plan)
    handles: dict[str, Any] = {}
    feed_errors: list[tuple[str, str]] = []

    def feeder(s) -> None:
        while pending:
            item = pending[0]
            if s.steps_run < item["feed_step"]:
                idle = s.queue_len() == 0 and all(
                    r is None for r in s.slots
                )
                if not idle:
                    return
            pending.popleft()
            try:
                handles[item["request_id"]] = s.submit(
                    item["prompt"], item["max_new"], item["sampling"],
                    streaming=item["streaming"],
                    request_id=item["request_id"],
                )
            except Exception as e:  # AdmissionRejected under overrides
                feed_errors.append(
                    (item["request_id"], f"{type(e).__name__}: {e}")
                )

    sched.replay_feeder = feeder
    # Adaptive fused-K reads queue depth, which is wall-clock-coupled:
    # the journal records the K actually chosen at each megastep
    # (fused_k on the fused_j==0 step entry), and replay re-applies that
    # plan instead of re-deriving it. A fuse_steps override drops the
    # plan — the what-if runs the overridden policy from scratch.
    if not (overrides and "fuse_steps" in overrides):
        plan_k = {
            int(e["step"]) - 1: int(e["fused_k"])
            for e in entries
            if e.get("kind") == "step" and e.get("fused_j") == 0
        }
        if plan_k:
            sched.replay_fuse_plan = plan_k
    sched.start()
    # The supervisor is part of the recorded machine: a journaled
    # engine_crash fault must revive and restart-replay exactly as the
    # live supervisor did. Tight poll — replay has no SLO to protect.
    sup = EngineSupervisor(sched, poll_s=0.05)
    sup.start()
    timed_out = False
    try:
        deadline = time.monotonic() + timeout_s
        while pending or not all(
            h.done.is_set() for h in handles.values()
        ):
            if sup.gave_up:
                break
            if time.monotonic() > deadline:
                timed_out = True
                break
            time.sleep(0.02)
    finally:
        gave_up = sup.gave_up
        sup.stop()
        sched.close()
        faults.configure(None)
    return {
        "entries": journal.snapshot(),
        "skipped": skipped,
        "feed_errors": feed_errors,
        "timed_out": timed_out,
        "gave_up": gave_up,
    }


# ---------------------------------------------------------------------------
# Comparison
# ---------------------------------------------------------------------------


def replayed_stream(entries: list[dict[str, Any]]
                    ) -> list[tuple[int | None, dict[str, Any]]]:
    """The comparison view of a journal: REPLAYED_KINDS only, volatile
    fields dropped, as (original seq, cleaned entry) pairs (the seq
    rides along for the divergence report only)."""
    out = []
    for e in entries:
        if e.get("kind") not in journal_lib.REPLAYED_KINDS:
            continue
        clean = {k: v for k, v in e.items() if k not in VOLATILE_FIELDS}
        out.append((e.get("seq"), clean))
    return out


def first_divergence(live_entries: list[dict[str, Any]],
                     replay_entries: list[dict[str, Any]]
                     ) -> dict[str, Any] | None:
    """None when the two decision streams are equal; else the first
    point of divergence: {index (into the replayed stream), seq (the
    LIVE journal's), kind, field, live, replay}. A stream ending early
    reports field "<missing>" with the absent side None."""
    live = replayed_stream(live_entries)
    rep = replayed_stream(replay_entries)
    for i in range(min(len(live), len(rep))):
        lseq, a = live[i]
        _, b = rep[i]
        if a == b:
            continue
        if a.get("kind") != b.get("kind"):
            field = "kind"
        else:
            field = next(
                k for k in sorted(set(a) | set(b))
                if a.get(k) != b.get(k)
            )
        return {
            "index": i, "seq": lseq, "kind": a.get("kind"),
            "field": field, "live": a.get(field), "replay": b.get(field),
        }
    if len(live) != len(rep):
        i = min(len(live), len(rep))
        seq, e = (live[i] if len(live) > len(rep) else rep[i])
        return {
            "index": i,
            "seq": seq if len(live) > len(rep) else None,
            "kind": e.get("kind"), "field": "<missing>",
            "live": e if len(live) > len(rep) else None,
            "replay": e if len(rep) > len(live) else None,
        }
    return None


def _finishes(entries: list[dict[str, Any]]) -> dict[str, dict[str, Any]]:
    return {
        e["request_id"]: e for e in entries if e["kind"] == "finish"
    }


def reply_match(live_entries: list[dict[str, Any]],
                replay_entries: list[dict[str, Any]]
                ) -> tuple[int, int, list[str]]:
    """(matched, total, mismatched request ids) over the live finish
    entries' reply-bytes + token-stream fingerprints."""
    live, rep = _finishes(live_entries), _finishes(replay_entries)
    bad = [
        rid for rid, e in live.items()
        if (r := rep.get(rid)) is None
        or r.get("reply_sha256") != e.get("reply_sha256")
        or r.get("tokens_sha256") != e.get("tokens_sha256")
    ]
    return len(live) - len(bad), len(live), sorted(bad)


# ---------------------------------------------------------------------------
# What-if diffing
# ---------------------------------------------------------------------------


def summarize(entries: list[dict[str, Any]]) -> dict[str, Any]:
    """Aggregate cost/goodput view of one journal — the quantities the
    what-if diff table compares."""
    fin = [e for e in entries if e["kind"] == "finish"]
    steps = [e for e in entries if e["kind"] == "step"]
    cost = {
        k: sum((e.get("cost") or {}).get(k) or 0 for e in fin)
        for k in journal_lib.DETERMINISTIC_COST_KEYS
    }
    dispatches = len(steps)
    return {
        "requests_finished": len(fin),
        "requests_ok": sum(1 for e in fin if e.get("status") == "ok"),
        "completion_tokens": sum(
            e.get("completion_tokens") or 0 for e in fin
        ),
        **{f"{k}_total": v for k, v in cost.items()},
        "peak_pages_max": max(
            ((e.get("cost") or {}).get("peak_pages") or 0 for e in fin),
            default=0,
        ),
        "dispatches": dispatches,
        "evictions": sum(1 for e in entries if e["kind"] == "evict"),
        "splices": sum(1 for e in entries if e["kind"] == "splice"),
        "spliced_tokens": sum(
            e.get("spliced_tokens") or 0
            for e in entries if e["kind"] == "splice"
        ),
        "faults": sum(1 for e in entries if e["kind"] == "fault"),
        "restarts": sum(1 for e in entries if e["kind"] == "restart"),
        "tokens_per_dispatch": (
            cost["decode_tokens"] / dispatches if dispatches else 0.0
        ),
        "accepted_per_dispatch": (
            sum(e.get("accepted_tokens") or 0 for e in steps)
            / dispatches if dispatches else 0.0
        ),
    }


# (series, direction, rel_tol): the diff table's shape. Goodput rows
# judge "higher is better", resource rows "lower", workload-identity
# rows are informational (the what-if replays the same requests, but
# overrides may legitimately change completion under faults).
_WHATIF_SERIES = (
    ("requests_finished", "info", 0.0),
    ("requests_ok", "info", 0.0),
    ("completion_tokens", "info", 0.0),
    ("decode_tokens_total", "info", 0.0),
    ("prefill_tokens_total", "info", 0.0),
    ("cached_tokens_total", "higher", 0.05),
    ("spliced_tokens", "higher", 0.05),
    ("decode_steps_total", "lower", 0.05),
    ("dispatches", "lower", 0.05),
    ("tokens_per_dispatch", "higher", 0.05),
    ("accepted_per_dispatch", "higher", 0.05),
    ("peak_pages_max", "lower", 0.05),
    ("evictions", "lower", 0.0),
    ("splices", "info", 0.0),
    ("faults", "info", 0.0),
    ("restarts", "lower", 0.0),
)


def whatif_rows(live_entries: list[dict[str, Any]],
                replay_entries: list[dict[str, Any]]
                ) -> list[dict[str, Any]]:
    """bench_compare-idiom rows (baseline = the live journal, current =
    the overridden replay), judged with bench_compare's own verdict
    logic so "improved"/"regression" mean exactly what the perf gates
    mean."""
    base, cur = summarize(live_entries), summarize(replay_entries)
    matched, total, _ = reply_match(live_entries, replay_entries)
    rows = []
    for series, direction, tol in _WHATIF_SERIES:
        row = bench_compare._judge(bench_compare.Row(
            series=series, baseline=base[series], current=cur[series],
            direction=direction, rel_tol=tol,
        ))
        rows.append(vars(row))
    rows.append(vars(bench_compare.Row(
        series="reply_bytes_identical",
        baseline=f"{total}/{total}", current=f"{matched}/{total}",
        direction="info", rel_tol=0.0, verdict="info",
        note="overrides may legally change sampling numerics",
    )))
    return rows


def validate_whatif_report(report: dict[str, Any]) -> list[str]:
    """Schema check for the --out what-if report; [] when valid."""
    problems = []
    for key in ("bench", "schema", "journal", "overrides", "rows",
                "baseline", "current"):
        if key not in report:
            problems.append(f"missing key {key!r}")
    if report.get("bench") != "replay_whatif":
        problems.append("bench != 'replay_whatif'")
    if report.get("schema") != WHATIF_SCHEMA:
        problems.append(f"schema != {WHATIF_SCHEMA}")
    for i, row in enumerate(report.get("rows") or []):
        missing = [k for k in WHATIF_ROW_KEYS if k not in row]
        if missing:
            problems.append(f"row {i} missing {missing}")
    if not report.get("rows"):
        problems.append("empty rows")
    return problems


def print_diff_table(rows: list[dict[str, Any]]) -> None:
    w = 58

    def fmt(v):
        if isinstance(v, bool):
            return str(v)
        if isinstance(v, (int, float)):
            return f"{v:g}"
        return "-" if v is None else str(v)

    print(f"{'series':<{w}} {'baseline':>12} {'current':>12} "
          f"{'tol':>6}  verdict")
    print("-" * (w + 42))
    for r in rows:
        print(f"{r['series'][:w]:<{w}} {fmt(r['baseline']):>12} "
              f"{fmt(r['current']):>12} {r['rel_tol']:>6g}  "
              f"{r['verdict'].upper()}"
              + (f" ({r['note']})" if r.get("note") else ""))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def parse_overrides(spec: str, base: dict[str, Any]) -> dict[str, Any]:
    """`k=v,k=v` against the OVERRIDE_KEYS whitelist, coercing each
    value to the header field's type (the header is the source of truth
    for what e.g. prefill_chunk *is*)."""
    out: dict[str, Any] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if not eq or key not in OVERRIDE_KEYS:
            raise SystemExit(
                f"unknown override {key!r} (allowed: "
                + ", ".join(OVERRIDE_KEYS) + ")"
            )
        out[key] = _coerce(val, base.get(key))
    return out


def _coerce(val: str, current: Any) -> Any:
    low = val.lower()
    if low in ("none", "null", ""):
        return None
    if isinstance(current, bool) or low in ("true", "false"):
        return low in ("1", "true", "yes", "on")
    try:
        return int(val)
    except ValueError:
        return val


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("journal", help="journal file from --journal PATH "
                    "(a rotated PATH.1 sibling is merged automatically)")
    ap.add_argument("--override", default=None, metavar="K=V[,K=V...]",
                    help="what-if mode: replay under altered flags "
                    "and diff cost/goodput instead of asserting "
                    "equality (keys: " + ", ".join(OVERRIDE_KEYS) + ")")
    ap.add_argument("--model-path", default=None,
                    help="replay against a real checkpoint "
                    "(default: the tiny self-test pipeline)")
    ap.add_argument("--shard", default=None,
                    help="shard spec for --model-path (e.g. tp=8)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="replay wall-clock budget in seconds")
    ap.add_argument("--out", default=None,
                    help="write the replay/what-if report JSON here")
    args = ap.parse_args(argv)

    header, entries = journal_lib.read_journal(args.journal)
    cfg = header.get("config") or {}
    print(f"journal: {args.journal}")
    print(f"  schema {header.get('schema')}  model "
          f"{cfg.get('model')!r}  engine {cfg.get('engine')!r}  "
          f"entries {len(entries)}")
    print("  geometry: " + " ".join(
        f"{k}={cfg.get(k)}" for k in GEOMETRY_KEYS if k in cfg
    ))
    if cfg.get("faults_spec"):
        print(f"  faults: {cfg['faults_spec']}")

    pipe = None
    if args.model_path:
        from oryx_tpu.serve.builder import load_pipeline

        pipe = load_pipeline(args.model_path, shard=args.shard)

    overrides = (
        parse_overrides(args.override, cfg) if args.override else None
    )
    if overrides:
        print("  overrides: " + " ".join(
            f"{k}={v}" for k, v in overrides.items()
        ))
    result = run_replay(
        header, entries, pipe=pipe, overrides=overrides,
        timeout_s=args.timeout,
    )
    for rid, why in result["skipped"]:
        print(f"  skipped {rid}: {why}")
    for rid, err in result["feed_errors"]:
        print(f"  feed error {rid}: {err}")
    if result["timed_out"]:
        print(f"REPLAY TIMED OUT after {args.timeout:g}s", file=sys.stderr)
    if result["gave_up"]:
        print("REPLAY SUPERVISOR GAVE UP (crash loop)", file=sys.stderr)

    if overrides:
        rows = whatif_rows(entries, result["entries"])
        print()
        print_diff_table(rows)
        report = {
            "bench": "replay_whatif", "schema": WHATIF_SCHEMA,
            "journal": str(args.journal), "overrides": overrides,
            "baseline": summarize(entries),
            "current": summarize(result["entries"]),
            "rows": rows,
            "skipped": result["skipped"],
            "feed_errors": result["feed_errors"],
        }
        problems = validate_whatif_report(report)
        if problems:
            print("INTERNAL: invalid what-if report: "
                  + "; ".join(problems), file=sys.stderr)
            return 2
        if args.out:
            Path(args.out).write_text(json.dumps(report, indent=2))
            print(f"\nwrote {args.out}")
        return 1 if (result["timed_out"] or result["gave_up"]) else 0

    div = first_divergence(entries, result["entries"])
    matched, total, bad = reply_match(entries, result["entries"])
    n_live = len(replayed_stream(entries))
    print(f"\nreplayed decisions: {n_live} live vs "
          f"{len(replayed_stream(result['entries']))} replay")
    print(f"reply bytes identical: {matched}/{total}"
          + (f"  (mismatched: {', '.join(bad)})" if bad else ""))
    if args.out:
        Path(args.out).write_text(json.dumps({
            "bench": "replay_faithful", "schema": WHATIF_SCHEMA,
            "journal": str(args.journal),
            "replies_matched": matched, "replies_total": total,
            "divergence": div,
            "skipped": result["skipped"],
            "feed_errors": result["feed_errors"],
        }, indent=2))
        print(f"wrote {args.out}")
    if div is not None:
        print("\nFIRST DIVERGENCE:", file=sys.stderr)
        for k in ("index", "seq", "kind", "field", "live", "replay"):
            print(f"  {k:>7}: {div[k]!r}", file=sys.stderr)
        return 2
    if result["timed_out"] or result["gave_up"] or result["feed_errors"]:
        return 2
    print("\nREPLAY OK: byte-identical replies, "
          "decision-for-decision equal, cost ledgers equal")
    return 0


if __name__ == "__main__":
    sys.exit(main())
