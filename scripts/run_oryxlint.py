#!/usr/bin/env python
"""oryxlint CLI: JAX-aware static analysis over the repo.

    python scripts/run_oryxlint.py                 # report, exit 1 on findings
    python scripts/run_oryxlint.py --strict        # CI gate (also fails on
                                                   # parse errors)
    python scripts/run_oryxlint.py --changed-only  # fast local loop (widens
                                                   # to the full tree when the
                                                   # linter/fixtures changed)
    python scripts/run_oryxlint.py --json path.py  # machine-readable
    python scripts/run_oryxlint.py --max-suppressions 25 \
        --json-out /tmp/oryxlint_report.json       # CI ratchet + artifact

The linter is pure-AST and must start fast in images without the
accelerator stack, so the real `oryx_tpu/__init__` (which imports jax)
is stubbed: only `oryx_tpu.analysis.*` — stdlib-only by design — is
actually executed. In-process consumers (tests) just import
`oryx_tpu.analysis` normally.
"""

import os
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_analysis():
    sys.path.insert(0, ROOT)
    if "oryx_tpu" not in sys.modules:
        stub = types.ModuleType("oryx_tpu")
        stub.__path__ = [os.path.join(ROOT, "oryx_tpu")]
        sys.modules["oryx_tpu"] = stub
    from oryx_tpu.analysis import runner

    return runner


if __name__ == "__main__":
    runner = _import_analysis()
    sys.exit(runner.main(sys.argv[1:]))
