#!/usr/bin/env bash
# Oryx-7B LoRA SFT (reference-equivalent: train.py --lora_enable True
# --lora_r 128 --lora_alpha 256, decoder projections adapted, base model
# frozen, projector co-trained; SURVEY.md §2 "Training entry"). LoRA
# shrinks trainable/optimizer state to the adapters, so this fits fewer
# chips than full FT. Merge for serving via models/oryx.merge_lora or
# export a PEFT adapter dir via models/import_hf.export_lora_dir.
set -euo pipefail
cd "$(dirname "$0")/.."

DATA=${DATA:?path to conversation-records json}
TOKENIZER=${TOKENIZER:?path to Qwen2 tokenizer dir}
HF_LLM=${HF_LLM:-}          # HF safetensors dir (Qwen2-7B-Instruct)
HF_VISION=${HF_VISION:-}    # HF safetensors dir (SigLIP-family tower)

python -m oryx_tpu.train.cli \
  --config scripts/configs/oryx_7b_sft_lora.json \
  --data "$DATA" \
  --tokenizer-path "$TOKENIZER" \
  ${HF_LLM:+--hf-llm "$HF_LLM"} \
  ${HF_VISION:+--hf-vision "$HF_VISION"} \
  --sharding fsdp \
  --metrics-path logs/oryx7b_lora_metrics.jsonl \
  --output-dir models/oryx7b-sft-lora \
  "$@"
