"""Capture an XLA profiler trace of the bench train step and print an
op-level summary — the "profile, iterate" loop for MFU work.

Runs the same geometry/config selection as bench.py (same env knobs:
BENCH_REMAT_POLICY, BENCH_LOSS_CHUNK, BENCH_MOMENT_DTYPE, BENCH_BATCH,
BENCH_SEQ), warms up, then traces TRACE_STEPS steps with
jax.profiler.trace and decodes the written xplane.pb with the
dependency-free reader in oryx_tpu/utils/xplane.py (the TF/tensorboard
profile tooling on this box is version-broken). Prints one JSON line:
top ops by total device time (TPU plane when present, host plane as
fallback on CPU smoke runs).

    TRACE_DIR=/tmp/oryx_trace python scripts/capture_trace.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRACE_STEPS = int(os.environ.get("TRACE_STEPS", "3"))
TOP_N = int(os.environ.get("TRACE_TOP_N", "30"))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from bench import _bench_cfg, _make_batch, chip_info
    from oryx_tpu.models import oryx
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer
    from oryx_tpu.utils import xplane

    trace_dir = os.environ.get("TRACE_DIR", "/tmp/oryx_trace")
    backend = jax.default_backend()
    _, hbm, _ = chip_info(jax)
    geo_name, cfg, batch_size, seq_bucket, img_side = _bench_cfg(backend, hbm)
    host = _make_batch(cfg, batch_size, seq_bucket, img_side)
    batch = {k: jnp.asarray(v)[None] for k, v in host.items()}

    params = oryx.init_params(cfg, jax.random.key(0))
    tx = make_optimizer(cfg.train, params)
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
    )

    # Warmup outside the trace: compile noise would dominate the profile.
    for _ in range(2):
        state, metrics = step_lib.train_step(state, batch, cfg, tx)
    jax.device_get(metrics["loss"])

    with jax.profiler.trace(trace_dir):
        for _ in range(TRACE_STEPS):
            state, metrics = step_lib.train_step(state, batch, cfg, tx)
        jax.device_get(metrics["loss"])

    files = xplane.find_xplane_files(trace_dir)
    if not files:
        print(json.dumps({"error": "no_xplane_written", "dir": trace_dir}))
        raise SystemExit(1)
    planes = xplane.parse_xspace(files[-1])
    device = xplane.top_ops(planes, n=TOP_N, plane_filter="TPU",
                            line_filter="Ops")
    if device:
        source, top = "tpu_xla_ops", device
    else:
        # Host fallback (CPU smoke): exclude any "Modules" aggregate
        # lines — a module event contains its ops' time, so summing both
        # would double-count and let one jit_train_step entry swamp the
        # per-op ranking.
        host_planes = [
            xplane.Plane(
                p.name,
                [l for l in p.lines if "Modules" not in l.name],
            )
            for p in planes
        ]
        source, top = "host_fallback", xplane.top_ops(host_planes, n=TOP_N)
    print(json.dumps({
        "metric": "trace_top_ops",
        "geometry": geo_name,
        "steps": TRACE_STEPS,
        "backend": backend,
        "source": source,
        "planes": [p.name for p in planes],
        "xplane": files[-1],
        "top_ops_ms": [
            {"op": name, "ms": round(ms, 3)} for name, ms in top
        ],
    }))


if __name__ == "__main__":
    main()
