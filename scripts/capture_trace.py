"""Capture an XLA profiler trace of the bench train step and print an
op-level summary — the "profile, iterate" loop for MFU work.

Runs the same geometry/config selection as bench.py (same env knobs:
BENCH_REMAT_POLICY, BENCH_LOSS_CHUNK, BENCH_MOMENT_DTYPE, BENCH_BATCH,
BENCH_SEQ), warms up, then traces TRACE_STEPS steps with
jax.profiler.trace and decodes the written xplane.pb with the
dependency-free reader in oryx_tpu/utils/xplane.py (the TF/tensorboard
profile tooling on this box is version-broken). Prints one JSON line:
top ops by total device time (TPU plane when present, host plane as
fallback on CPU smoke runs).

    TRACE_DIR=/tmp/oryx_trace python scripts/capture_trace.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRACE_STEPS = int(os.environ.get("TRACE_STEPS", "3"))
TOP_N = int(os.environ.get("TRACE_TOP_N", "30"))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from bench import _bench_cfg, _make_batch, chip_info
    from oryx_tpu.models import oryx
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer
    from oryx_tpu.utils import profiling

    trace_dir = os.environ.get("TRACE_DIR", "/tmp/oryx_trace")
    backend = jax.default_backend()
    _, hbm, _ = chip_info(jax)
    geo_name, cfg, batch_size, seq_bucket, img_side = _bench_cfg(backend, hbm)
    host = _make_batch(cfg, batch_size, seq_bucket, img_side)
    batch = {k: jnp.asarray(v)[None] for k, v in host.items()}

    params = oryx.init_params(cfg, jax.random.key(0))
    tx = make_optimizer(cfg.train, params)
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
    )

    # Warmup outside the trace: compile noise would dominate the profile.
    # The carry threads through so every traced step is a REAL step (a
    # repeated identical step could be elided by donation aliasing).
    holder = {"state": state}

    def one_step():
        holder["state"], metrics = step_lib.train_step(
            holder["state"], batch, cfg, tx
        )
        return metrics["loss"]

    for _ in range(2):
        loss = one_step()
    jax.device_get(loss)

    try:
        prof = profiling.op_profile(
            one_step, trace_dir=trace_dir, steps=TRACE_STEPS, top_n=TOP_N,
            sync=jax.device_get,  # block_until_ready is a no-op over axon
        )
    except RuntimeError as e:  # no xplane written (e.g. trace aborted)
        print(json.dumps({"error": "no_xplane_written", "detail": str(e)}))
        raise SystemExit(1)
    except ValueError as e:  # truncated xplane (profiler killed mid-write)
        print(json.dumps({"error": "corrupt_xplane", "detail": str(e)}))
        raise SystemExit(1)
    print(json.dumps({
        "metric": "trace_top_ops",
        "geometry": geo_name,
        "steps": TRACE_STEPS,
        "backend": backend,
        # source=host_fallback on a TPU run means the device plane was
        # NOT found — host dispatch noise, not device op time.
        "source": prof.source,
        "planes": prof.plane_names,
        "xplane": prof.xplane_path,
        "top_ops_ms": [
            {"op": name, "ms": round(ms, 3)} for name, ms in prof.top
        ],
    }))


if __name__ == "__main__":
    main()
