"""Capture an XLA profiler trace of the bench train step and print an
op-level summary — the "profile, iterate" loop for MFU work.

Runs the same geometry/config selection as bench.py (same env knobs:
BENCH_REMAT_POLICY, BENCH_LOSS_CHUNK, BENCH_MOMENT_DTYPE, BENCH_BATCH,
BENCH_SEQ), warms up, then traces TRACE_STEPS steps with
jax.profiler.trace and decodes the written xplane.pb with the
dependency-free reader in oryx_tpu/utils/xplane.py (the TF/tensorboard
profile tooling on this box is version-broken). Prints one JSON line:
top ops by total device time (TPU plane when present, host plane as
fallback on CPU smoke runs).

Each traced step also records a host-side span (utils/trace.py, the
same machinery behind the serving flight recorder), and the written
xplane is joined back against those windows — per-step device time
attributed to host spans ("span_device_ms"), closing the loop between
live tracing and on-chip profiles. To join a LIVE recording instead —
e.g. decode-chunk spans exported from a serving run's flight recorder
(Tracer.write_jsonl / GET /debug/trace) — point TRACE_SPANS at the
JSONL and TRACE_SPAN_NAME at the span to attribute (default
decode_chunk); the windows then come from that file rather than the
steps traced here.

    TRACE_DIR=/tmp/oryx_trace python scripts/capture_trace.py
    TRACE_SPANS=flight.jsonl python scripts/capture_trace.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRACE_STEPS = int(os.environ.get("TRACE_STEPS", "3"))
TOP_N = int(os.environ.get("TRACE_TOP_N", "30"))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from bench import _bench_cfg, _make_batch, chip_info
    from oryx_tpu.models import oryx
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer
    from oryx_tpu.utils import profiling
    from oryx_tpu.utils import trace as trace_lib
    from oryx_tpu.utils import xplane

    trace_dir = os.environ.get("TRACE_DIR", "/tmp/oryx_trace")
    backend = jax.default_backend()
    _, hbm, _ = chip_info(jax)
    geo_name, cfg, batch_size, seq_bucket, img_side = _bench_cfg(backend, hbm)
    host = _make_batch(cfg, batch_size, seq_bucket, img_side)
    batch = {k: jnp.asarray(v)[None] for k, v in host.items()}

    params = oryx.init_params(cfg, jax.random.key(0))
    tx = make_optimizer(cfg.train, params)
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
    )

    # Warmup outside the trace: compile noise would dominate the profile.
    # The carry threads through so every traced step is a REAL step (a
    # repeated identical step could be elided by donation aliasing).
    holder = {"state": state}

    def one_step():
        holder["state"], metrics = step_lib.train_step(
            holder["state"], batch, cfg, tx
        )
        return metrics["loss"]

    for _ in range(2):
        loss = one_step()
    jax.device_get(loss)

    # Host-side step spans for the post-hoc span<->xplane join. The
    # per-step device_get sync pins each window around its step's real
    # device execution (async dispatch would otherwise close the window
    # before the device ran) — attribution mode trades a little overlap
    # for attributable windows.
    tracer = trace_lib.Tracer(max(TRACE_STEPS, 4))
    steps_trace = tracer.start_trace("profile", label="capture_trace")

    def traced_step():
        with steps_trace.span("train_step"):
            out = one_step()
            jax.device_get(out)
        return out

    try:
        prof = profiling.op_profile(
            traced_step, trace_dir=trace_dir, steps=TRACE_STEPS,
            top_n=TOP_N,
            sync=jax.device_get,  # block_until_ready is a no-op over axon
        )
    except RuntimeError as e:  # no xplane written (e.g. trace aborted)
        print(json.dumps({"error": "no_xplane_written", "detail": str(e)}))
        raise SystemExit(1)
    except ValueError as e:  # truncated xplane (profiler killed mid-write)
        print(json.dumps({"error": "corrupt_xplane", "detail": str(e)}))
        raise SystemExit(1)
    steps_trace.finish()

    # Join device time back onto host spans: the traced steps above, or
    # — with TRACE_SPANS — an exported flight recorder from a live run
    # (e.g. the serving scheduler's decode-chunk spans).
    if spans_path := os.environ.get("TRACE_SPANS"):
        windows = trace_lib.windows_from_jsonl(
            spans_path, os.environ.get("TRACE_SPAN_NAME", "decode_chunk")
        )
    else:
        windows = trace_lib.windows_from_traces(
            [steps_trace.to_dict()], "train_step"
        )
    planes = xplane.parse_xspace(prof.xplane_path)
    filters = (
        {"plane_filter": "TPU", "line_filter": "Ops"}
        if prof.source == "tpu_xla_ops" else {}
    )
    attributed = xplane.attribute_device_time(
        planes, windows, session_end_ns=prof.trace_end_ns, **filters
    )
    print(json.dumps({
        "metric": "trace_top_ops",
        "geometry": geo_name,
        "steps": TRACE_STEPS,
        "backend": backend,
        # source=host_fallback on a TPU run means the device plane was
        # NOT found — host dispatch noise, not device op time.
        "source": prof.source,
        "planes": prof.plane_names,
        "xplane": prof.xplane_path,
        "top_ops_ms": [
            {"op": name, "ms": round(ms, 3)} for name, ms in prof.top
        ],
        # Device time attributed per host span window (the join); a
        # dominant _unattributed bucket means the clocks didn't line up
        # or the windows came from a different run than the xplane.
        "span_device_ms": {
            label: round(ps / 1e9, 3)
            for label, ps in sorted(attributed.items())
        },
    }))


if __name__ == "__main__":
    main()
