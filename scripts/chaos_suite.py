"""Chaos suite: every named fault scenario against a LIVE tiny server,
asserting the invariant triad after each one.

Scenarios (one armed `utils/faults.py` spec each, fully deterministic):

  * ``page_alloc_oom``    injected pool exhaustion during a concurrent
                          shared-prefix burst — defer/evict absorbs it;
                          every request still answers 200.
  * ``engine_crash``      engine-thread death mid-decode — the
                          EngineSupervisor restarts with deterministic
                          replay; the client's reply is byte-identical
                          to the solo pipeline and /readyz recovers.
  * ``journaled_crash``   the same engine-thread death with the
                          decision journal armed (--journal): the
                          fault firing and supervisor restart land in
                          the journal, and scripts/replay_journal.py
                          replays the file offline bit-for-bit —
                          decision-for-decision equal, reply
                          fingerprints identical.
  * ``hung_dispatch``     a decode dispatch stalls past the
                          per-request deadline — the request converts
                          into a clean 504, pages freed.
  * ``client_disconnect`` the SSE write path raises BrokenPipeError
                          (the dropped-socket code path) — the request
                          cancels, pages and cache shares freed.
  * ``spec_drift``        an oracle drafter degrades mid-run into
                          proposing garbage — the spec_accept_collapse
                          detector fires EXACTLY ONE event for the
                          whole episode, replies stay byte-identical
                          (rejected drafts are dead lanes), zero leaks.
  * ``checkpoint_save``   injected save failures — bounded
                          exponential-backoff retry lands the
                          checkpoint; the schedule is pinned (no
                          wall-clock sleeps).

The invariant triad, asserted after EVERY serving scenario:

  1. pool `check_invariant(holders)` holds — every page free or
     exactly accounted to its holders (slots + prefix cache);
  2. zero leaked pages/refcounts — with all slots idle, free pages +
     cache-held pages == the whole pool;
  3. the server RETURNS TO SERVING — /readyz 200, a fresh completion
     answers 200, and `oryx_faults_injected_total{site=}` in /metrics
     reconciles exactly against the injection schedule's own count.

Exit 0 = all scenarios contained; nonzero prints the failing scenario.
Wired into scripts/check_tier1.sh. See docs/OBSERVABILITY.md "Failure
playbook" for what each scenario looks like in production telemetry.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""
# A chaos run must never inherit ambient fault specs on top of the
# per-scenario ones this script arms itself.
os.environ.pop("ORYX_FAULTS", None)


class _Tokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def wait_for(predicate, timeout=120.0, what="condition") -> None:
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if predicate():
            return
        time.sleep(0.05)
    fail(f"timed out waiting for {what}")


class Harness:
    """One tiny in-process server per scenario: build, run the
    scenario body, assert the triad, tear down."""

    def __init__(self, pipe):
        self.pipe = pipe

    def boot(self, faults_spec: str, **server_kw):
        from oryx_tpu.serve import api_server

        srv = api_server.build_server(
            self.pipe, port=0, engine="continuous", num_slots=2,
            page_size=16, decode_chunk=4, max_ctx=512,
            faults_spec=faults_spec, **server_kw,
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, f"http://127.0.0.1:{srv.server_address[1]}"

    def teardown(self, srv) -> None:
        from oryx_tpu.utils import faults

        faults.reset()
        if srv.supervisor is not None:
            srv.supervisor.stop()
        if srv.scheduler is not None:
            srv.scheduler.close()
        srv.shutdown()

    # -- HTTP helpers (utils/retry.urlopen_json: rides out the engine
    # -- restart window instead of failing on one refused connect) ----

    def get(self, url: str, **kw):
        from oryx_tpu.utils.retry import urlopen_json

        return urlopen_json(url, **kw)

    def post_chat(self, base: str, content: str, max_tokens: int,
                  timeout: float = 600.0):
        return self.get(
            base + "/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": content}],
                "max_tokens": max_tokens,
            }).encode(),
            headers={"Content-Type": "application/json"},
            timeout=timeout,
        )

    # -- the triad -----------------------------------------------------

    def assert_triad(self, srv, base: str, scenario: str,
                    sites: list[str]) -> None:
        from oryx_tpu.utils import faults

        sched = srv.scheduler
        wait_for(
            lambda: all(r is None for r in sched.slots)
            and sched.queue_len() == 0,
            what=f"[{scenario}] slots+queue to empty",
        )
        # 1. Pool invariant: every page free or exactly accounted.
        sched._check_pool_invariant()
        # 2. Zero leaks: with no residents, only the prefix cache may
        #    hold pages. (The cache is engine-thread-owned; this read
        #    is legal because the wait above proved quiescence — say
        #    so to the armed race detector instead of tripping it.)
        from oryx_tpu.analysis.sanitizers import race_exempt

        with race_exempt("zero-leak check after quiesce"):
            cache_pages = (
                len(sched.prefix_cache.held_pages())
                if sched.prefix_cache is not None else 0
            )
        if sched.allocator.num_free + cache_pages != sched.num_pages:
            fail(f"[{scenario}] leaked pages: free "
                 f"{sched.allocator.num_free} + cache {cache_pages} "
                 f"!= pool {sched.num_pages}")
        # 3a. Back to serving: /readyz 200 and a real completion works.
        status, body, _ = self.get(base + "/readyz", timeout=30)
        if status != 200 or body.get("ready") is not True:
            fail(f"[{scenario}] /readyz after the scenario: want "
                 f"200/true, got {status} {body}")
        status, body, _ = self.post_chat(base, "post-chaos probe", 3)
        if status != 200:
            fail(f"[{scenario}] post-scenario completion: want 200, "
                 f"got {status} {body}")
        # 3b. Metric reconciliation: what /metrics says happened is
        #     exactly what the armed schedule says it injected.
        import urllib.request

        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            if r.status != 200:
                fail(f"[{scenario}] /metrics scrape: want 200, got "
                     f"{r.status}")
            text = r.read().decode()
        total = 0
        for site in sites:
            m = re.search(
                rf'^oryx_faults_injected_total\{{site="{site}"\}} '
                rf"([0-9.e+-]+)$", text, re.M,
            )
            metric = float(m.group(1)) if m else 0.0
            count = faults.injected_count(site)
            if metric != count:
                fail(f"[{scenario}] oryx_faults_injected_total"
                     f'{{site="{site}"}} is {metric}, injector '
                     f"counted {count}")
            total += count
        print(f"  [{scenario}] contained: invariant holds, 0 leaks, "
              f"/readyz 200, {total} fault(s) injected and accounted")


# ---------------------------------------------------------------------
# Scenarios
# ---------------------------------------------------------------------


def scenario_page_alloc_oom(h: Harness) -> None:
    """Injected pool exhaustion on a deterministic schedule while a
    shared-prefix burst runs: allocation failure is a scheduling
    signal (defer / evict / COW-fallback) — every request answers."""
    srv, base = h.boot("page_alloc_oom:every=3,times=6")
    try:
        sysprompt = "shared prefix for the chaos burst to splice! "
        results: list[tuple[int, object]] = []

        def one(i: int) -> None:
            status, body, _ = h.post_chat(
                base, sysprompt + f"q{i}", 3 + i % 2
            )
            results.append((status, body))

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        bad = [r for r in results if r[0] != 200]
        if bad:
            fail(f"[page_alloc_oom] burst requests failed under "
                 f"injected OOM: {bad}")
        h.assert_triad(srv, base, "page_alloc_oom", ["page_alloc_oom"])
        # Forensics: every injected OOM must have left one bounded
        # record in the ring (pool summary reconciled at capture
        # time, top-K residents named), and the post-incident pool
        # map must reconcile — the capacity incident is diagnosable
        # AFTER the fact from /debug/oom alone.
        from oryx_tpu.utils import faults

        injected = faults.injected_count("page_alloc_oom")
        import urllib.request as _url

        with _url.urlopen(base + "/metrics", timeout=30) as r:
            mtext = r.read().decode()
        m = re.search(
            r'^oryx_serving_oom_forensics_total\{trigger="oom"\} '
            r"([0-9.e+-]+)$", mtext, re.M,
        )
        raised = float(m.group(1)) if m else 0.0
        # Every injected raise captures exactly one trigger="oom"
        # record (genuine free-list-shortfall episodes capture their
        # own trigger="pool_pressure" records and are not counted
        # against the injector).
        if raised != injected:
            fail(f"[page_alloc_oom] {raised:g} trigger=oom forensic "
                 f"record(s), injector counted {injected}")
        status, recs, _ = h.get(base + "/debug/oom?n=64", timeout=30)
        if status != 200 or recs.get("total", 0) < injected:
            fail(f"[page_alloc_oom] /debug/oom holds "
                 f"{recs.get('total')} record(s), want >= {injected}")
        for rec in recs.get("records") or []:
            if not rec.get("top_requests"):
                fail(f"[page_alloc_oom] forensic record "
                     f"#{rec.get('index')} has an empty top-K")
            if not (rec.get("pool") or {}).get("reconciled"):
                fail(f"[page_alloc_oom] forensic record "
                     f"#{rec.get('index')} captured an unreconciled "
                     f"pool: {rec.get('pool')}")
        status, pages, _ = h.get(
            base + "/debug/pages?format=summary", timeout=30
        )
        s = pages.get("summary") or {}
        if status != 200 or not s.get("reconciled") \
                or s.get("slot") != 0:
            fail(f"[page_alloc_oom] post-incident /debug/pages does "
                 f"not reconcile: {s}")
        print(f"  [page_alloc_oom] forensics: {injected} injected "
              f"OOM(s) -> {injected} trigger=oom record(s) "
              f"({recs.get('total')} total), pool map reconciled")
    finally:
        h.teardown(srv)


def scenario_engine_crash(h: Harness) -> None:
    """Engine-thread death mid-flight: the supervisor restarts the
    loop, the in-flight request replays deterministically, and the
    client's reply is byte-identical to the solo pipeline."""
    q, m = "hello there chaos", 10
    ref = h.pipe.chat(q, max_new_tokens=m)
    srv, base = h.boot("engine_crash:after=2")
    try:
        status, body, _ = h.post_chat(base, q, m)
        if status != 200:
            fail(f"[engine_crash] request through the crash: want "
                 f"200, got {status} {body}")
        reply = body["choices"][0]["message"]["content"]
        if reply != ref:
            fail(f"[engine_crash] replayed reply {reply!r} != solo "
                 f"pipeline {ref!r} — replay was not deterministic")
        wait_for(lambda: srv.scheduler.restarts >= 1, timeout=30,
                 what="[engine_crash] supervisor restart")
        if srv.metrics.get("engine_restarts_total") < 1:
            fail("[engine_crash] engine_restarts_total never moved")
        h.assert_triad(srv, base, "engine_crash", ["engine_crash"])
    finally:
        h.teardown(srv)


def scenario_journaled_crash(h: Harness) -> None:
    """The flight-recorder contract under chaos: a crash mid-burst is
    JOURNALED (--journal armed; fault firing + supervisor restart
    entries in the stream), and the journal file replays offline
    bit-for-bit — fault, restart and every decision reproduced, reply
    fingerprints identical (docs/OBSERVABILITY.md "Incident replay")."""
    import tempfile

    from oryx_tpu.serve import journal as journal_lib

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import replay_journal as rj

    jpath = os.path.join(tempfile.mkdtemp(), "journal.jsonl")
    srv, base = h.boot("engine_crash:after=3", journal_path=jpath)
    try:
        for i in range(3):
            status, body, _ = h.post_chat(
                base, f"journal me through the crash q{i}", 4 + i % 3
            )
            if status != 200:
                fail(f"[journaled_crash] request {i} through the "
                     f"crash: want 200, got {status} {body}")
        wait_for(lambda: srv.scheduler.restarts >= 1, timeout=30,
                 what="[journaled_crash] supervisor restart")
        h.assert_triad(srv, base, "journaled_crash", ["engine_crash"])
        # Quiesce the live engine, then replay the file offline.
        if srv.supervisor is not None:
            srv.supervisor.stop()
        srv.scheduler.close()
        header, entries = journal_lib.read_journal(jpath)
        kinds = {e.get("kind") for e in entries}
        if "fault" not in kinds or "restart" not in kinds:
            fail(f"[journaled_crash] the crash did not journal: kinds "
                 f"{sorted(kinds)} lack fault/restart")
        res = rj.run_replay(header, entries, pipe=h.pipe)
        if res["feed_errors"] or res["timed_out"] or res["gave_up"]:
            fail(f"[journaled_crash] offline replay did not run "
                 f"clean: feed_errors={res['feed_errors']} "
                 f"timed_out={res['timed_out']} gave_up={res['gave_up']}")
        div = rj.first_divergence(entries, res["entries"])
        if div is not None:
            fail(f"[journaled_crash] offline replay diverged from the "
                 f"live journal: {div}")
        matched, total, bad = rj.reply_match(entries, res["entries"])
        if matched != total or total < 3:
            fail(f"[journaled_crash] replayed reply fingerprints: "
                 f"{matched}/{total} matched (divergent ids {bad})")
        print(f"  [journaled_crash] replayed: crash + restart "
              f"journaled ({len(entries)} entries), offline replay "
              f"decision-for-decision equal, {matched}/{total} reply "
              "fingerprints identical")
    finally:
        h.teardown(srv)


def scenario_hung_dispatch(h: Harness) -> None:
    """The FIRST decode dispatch stalls past the per-request deadline:
    the next step boundary converts the hang into a clean 504 and
    frees the slot's pages."""
    srv, base = h.boot(
        "decode_dispatch:delay=2.0,after=0", request_timeout=0.75,
    )
    try:
        status, body, _ = h.post_chat(base, "about to hang", 64)
        if status != 504:
            fail(f"[hung_dispatch] want 504 from the deadline, got "
                 f"{status} {body}")
        if body["error"]["type"] != "timeout_error":
            fail(f"[hung_dispatch] error type {body['error']} is not "
                 "timeout_error")
        if srv.metrics.get("deadline_exceeded_total") < 1:
            fail("[hung_dispatch] deadline_exceeded_total never moved")
        # The post-scenario probe in the triad must NOT inherit the
        # deadline that 504s everything — lift it (server default for
        # new requests only; the scenario's own request already ran).
        srv.scheduler.request_timeout = None
        h.assert_triad(srv, base, "hung_dispatch", ["decode_dispatch"])
    finally:
        h.teardown(srv)


def scenario_client_disconnect(h: Harness) -> None:
    """The SSE write path raises BrokenPipeError (the exact dropped-
    socket code path): the request cancels and its pages and
    prefix-cache shares come back."""
    import urllib.error
    import urllib.request

    srv, base = h.boot("client_disconnect:after=0")
    try:
        req = urllib.request.Request(
            base + "/v1/chat/completions",
            data=json.dumps({
                "messages": [
                    {"role": "user", "content": "stream then vanish"}
                ],
                "max_tokens": 200, "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        # The injected BrokenPipeError kills the response mid-stream;
        # whatever the client sees (truncated body, reset) is fine —
        # the assertion is server-side.
        # fault-boundary: the client half of an injected disconnect
        try:
            with urllib.request.urlopen(req, timeout=600) as r:
                r.read()
        except (OSError, urllib.error.URLError):
            pass
        wait_for(lambda: srv.metrics.get("cancelled") >= 1,
                 what="[client_disconnect] cancellation")
        h.assert_triad(
            srv, base, "client_disconnect", ["client_disconnect"]
        )
    finally:
        h.teardown(srv)


def scenario_spec_drift(h: Harness) -> None:
    """Speculation drift guard (ISSUE 14 satellite): an ORACLE drafter
    (proposes the request's known future — accept rate k+1) degrades
    mid-run into proposing garbage (accept rate collapses to 1.0).
    The spec_accept_collapse detector — default-armed whenever
    --speculate is set — must fire EXACTLY ONE event for the whole
    degraded phase (one page per episode, not one per dispatch), and
    the engine must stay healthy: every reply byte-identical to the
    solo pipeline, pool invariant intact, zero leaks."""
    from oryx_tpu.models import generate as gen_lib
    from oryx_tpu.serve.scheduler import ContinuousScheduler
    from oryx_tpu.utils.anomaly import AnomalyMonitor

    q, cap = "tell me a long story please", 40
    ref = h.pipe.chat(q, max_new_tokens=cap)
    ids = len(h.pipe._prepare_request({"question": q})[0])

    class Tap(gen_lib.Drafter):
        def __init__(self):
            self.longest: list[int] = []

        def propose(self, context, k):
            ctx = [int(x) for x in context]
            if len(ctx) > len(self.longest):
                self.longest = ctx
            return []

    # Record the greedy reply's token stream with a pure-observer
    # drafter (the engine then behaves exactly like the plain path).
    tap = Tap()
    sched = ContinuousScheduler(
        h.pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        prefill_chunk=8, ragged=True, speculate=1, drafter=tap,
        autostart=False, prefix_cache=False,
    )
    hd = sched.submit({"question": q}, cap)
    sched.start()
    if hd.result(timeout=600)[0] != ref:
        fail("[spec_drift] tap run diverged from the solo pipeline")
    sched.close()
    stream = tap.longest[ids:]

    class DegradableOracle(gen_lib.Drafter):
        """Perfect drafts until degrade(); garbage after."""

        def __init__(self, prompt_len: int, stream: list[int]):
            self.prompt_len = prompt_len
            self.stream = stream
            self.degraded = False

        def degrade(self):
            self.degraded = True

        def propose(self, context, k):
            if self.degraded:
                return [7] * k  # (almost) always rejected on greedy
            done = len(context) - self.prompt_len
            return self.stream[done: done + k]

    oracle = DegradableOracle(ids, stream)
    monitor = AnomalyMonitor(source="serve")
    sched = ContinuousScheduler(
        h.pipe, num_slots=2, page_size=16, chunk=4, max_ctx=512,
        prefill_chunk=8, ragged=True, speculate=3, drafter=oracle,
        anomaly=monitor, autostart=False, prefix_cache=False,
    )
    sched.start()
    try:
        # Healthy phase: enough spec dispatches to build the rolling
        # baseline (min_window) at the oracle's high accept rate.
        for _ in range(2):
            hd = sched.submit({"question": q}, cap)
            if hd.result(timeout=600)[0] != ref:
                fail("[spec_drift] healthy-phase reply diverged")
        if monitor.counts.get("spec_accept_collapse", 0):
            fail("[spec_drift] detector fired during the HEALTHY phase")
        # Mid-run degradation: the drafter starts proposing garbage.
        oracle.degrade()
        for _ in range(2):
            hd = sched.submit({"question": q}, cap)
            if hd.result(timeout=600)[0] != ref:
                fail("[spec_drift] degraded-phase reply diverged — "
                     "rejected drafts must not corrupt the stream")
        fired = monitor.counts.get("spec_accept_collapse", 0)
        if fired != 1:
            fail(f"[spec_drift] spec_accept_collapse fired {fired} "
                 "time(s) across the degraded phase, want exactly 1 "
                 "(one event per episode)")
        sched._check_pool_invariant()
        held = sum(
            1 for p in range(sched.allocator.num_pages)
            if sched.allocator.refcount(p) > 0
        )
        if held:
            fail(f"[spec_drift] {held} page(s) still held after the "
                 "degraded phase drained")
    finally:
        sched.close()
        monitor.close()
    print("  [spec_drift] contained: oracle degraded mid-run -> "
          "exactly 1 spec_accept_collapse event, replies "
          "byte-identical, 0 leaks")


def scenario_host_spill_upload(h: Harness) -> None:
    """Host spill-tier re-upload failure (site `host_spill_upload`,
    one injected raise) on an int8 pool with the host tier armed: a
    prompt is served cold, its cached prefix is force-spilled to host
    RAM, and the SAME prompt is re-sent — the injected upload failure
    must degrade the reload to a cold recompute (byte-identical 200
    reply, never a crash), with the pool invariant and zero leaks
    after the incident; a third send proves the tier recovered."""
    srv, base = h.boot(
        "host_spill_upload:times=1",
        kv_dtype="int8", host_cache_bytes=1 << 24, prefill_chunk=32,
    )
    try:
        prompt = "host tier chaos shared prefix " * 4
        status, cold, _ = h.post_chat(base, prompt, 6)
        if status != 200:
            fail(f"[host_spill_upload] cold request: {status} {cold}")
        cold_text = cold["choices"][0]["message"]["content"]
        sched = srv.scheduler
        wait_for(
            lambda: all(r is None for r in sched.slots)
            and sched.queue_len() == 0,
            what="[host_spill_upload] quiesce before the forced spill",
        )
        from oryx_tpu.analysis.sanitizers import race_exempt

        with race_exempt("forced cache spill: engine quiesced by the "
                         "wait above"):
            cache = sched.prefix_cache
            cache.evict(cache.evictable_pages())
            spilled = cache.spilled_pages
        if not spilled:
            fail("[host_spill_upload] forced eviction spilled nothing "
                 "(tier not armed?)")
        # Re-send: the reload attempt hits the injected failure and
        # must fall back to a cold recompute of the whole prefix.
        status, warm, _ = h.post_chat(base, prompt, 6)
        if status != 200:
            fail(f"[host_spill_upload] re-send under injected upload "
                 f"failure: {status} {warm}")
        warm_text = warm["choices"][0]["message"]["content"]
        if warm_text != cold_text:
            fail("[host_spill_upload] degraded (cold-recompute) reply "
                 f"diverged: {warm_text!r} != {cold_text!r}")
        # Third send: the fault schedule is exhausted and the cold
        # recompute re-donated the prefix — a normal cached hit.
        status, third, _ = h.post_chat(base, prompt, 6)
        if status != 200 or (
            third["choices"][0]["message"]["content"] != cold_text
        ):
            fail(f"[host_spill_upload] post-incident send: {status} "
                 f"{third}")
        h.assert_triad(
            srv, base, "host_spill_upload", ["host_spill_upload"]
        )
    finally:
        h.teardown(srv)


def scenario_checkpoint_save(h: Harness) -> None:
    """Two injected save failures: bounded backoff retries land the
    checkpoint on the third attempt, schedule pinned (no wall-clock
    sleeps), and the fault metric reconciles in the bound registry."""
    import tempfile

    import numpy as np

    from oryx_tpu.utils import faults
    from oryx_tpu.utils.checkpoint import CheckpointManager
    from oryx_tpu.utils.metrics import Registry
    from oryx_tpu.utils.retry import BackoffPolicy

    faults.configure("checkpoint_save:times=2")
    reg = Registry()  # raw-named family only; no prefix needed
    faults.bind_registry(reg)
    slept: list[float] = []
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(
            os.path.join(d, "ck"),
            save_retry=BackoffPolicy(retries=3, base_s=0.5,
                                     factor=2.0, jitter=0.0),
            sleep=slept.append,
        )
        try:
            state = {"x": np.arange(16, dtype=np.float32)}
            if mgr.save(1, state) is not True:
                fail("[checkpoint_save] save did not land")
            mgr.wait()
            if mgr.latest_step() != 1:
                fail("[checkpoint_save] latest_step != 1 after "
                     "retried save")
            restored = mgr.restore(None)
            if not np.array_equal(np.asarray(restored["x"]),
                                  state["x"]):
                fail("[checkpoint_save] restored state differs")
        finally:
            mgr.close()
    if slept != [0.5, 1.0]:
        fail(f"[checkpoint_save] backoff schedule {slept} != "
             "[0.5, 1.0] — retry policy drifted")
    m = re.search(
        r'^oryx_faults_injected_total\{site="checkpoint_save"\} '
        r"([0-9.e+-]+)$", reg.render(), re.M,
    )
    metric = float(m.group(1)) if m else 0.0
    if metric != 2 or faults.injected_count("checkpoint_save") != 2:
        fail(f"[checkpoint_save] injected-count mismatch: metric "
             f"{metric}, counter "
             f"{faults.injected_count('checkpoint_save')}, want 2")
    faults.reset()
    print("  [checkpoint_save] contained: 2 injected failures, "
          "pinned backoff [0.5, 1.0], checkpoint landed + restored, "
          "2 fault(s) accounted")


def main() -> None:
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.analysis import sanitizers
    from oryx_tpu.models import oryx
    from oryx_tpu.serve.pipeline import OryxInference

    # ORYX_LOCK_SANITIZER=1 (how check_tier1.sh runs this): every
    # scenario — crash, restart, hung dispatch, disconnect — executes
    # with instrumented locks and the guarded-field race detector
    # armed, and the suite fails on ANY recorded ordering violation,
    # race, or re-entrant scheduler._cond acquire. Chaos is exactly
    # when lock ordering bugs surface: restart/drain/fail_inflight are
    # the rarely-trodden paths.
    san_armed = sanitizers.maybe_arm_from_env()
    if san_armed:
        print("lock sanitizer ARMED for this chaos run "
              "(ordering violations raise at the faulty acquire)")

    t0 = time.monotonic()
    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_Tokenizer(), params, cfg)
    h = Harness(pipe)
    print("chaos suite: 8 scenarios against a live tiny server")
    for scenario in (
        scenario_page_alloc_oom,
        scenario_engine_crash,
        scenario_journaled_crash,
        scenario_hung_dispatch,
        scenario_client_disconnect,
        scenario_spec_drift,
        scenario_host_spill_upload,
        scenario_checkpoint_save,
    ):
        scenario(h)
    if san_armed:
        stats = sanitizers.lock_stats()
        if stats.violations:
            fail("lock-order sanitizer recorded violations during the "
                 f"chaos run: {stats.violations}")
        races = sanitizers.race_violations()
        if races:
            fail(f"race detector recorded violations: {races}")
        reentrant = stats.reentrant.get("scheduler._cond", 0)
        if reentrant:
            fail(f"scheduler._cond was re-acquired re-entrantly "
                 f"{reentrant} time(s) — the supervisor restart path "
                 "must take and release it per request")
        if not stats.acquires.get("scheduler._cond"):
            fail("sanitizer armed but saw no scheduler._cond acquires "
                 "— instrumentation did not take effect")
        print(f"  lock sanitizer: 0 violations, 0 races, 0 re-entrant "
              f"_cond acquires across "
              f"{sum(stats.acquires.values())} instrumented acquires")
    print(f"chaos suite OK: every fault contained, every pool "
          f"invariant held ({time.monotonic() - t0:.0f}s)")


if __name__ == "__main__":
    main()
