"""AOT per-chip memory proof: Oryx-7B SFT on a 16-device FSDP mesh.

Answers SURVEY.md §7 hard part 5 ("does the 7B train state actually fit
a v5e-16?") without 16 chips: lowers + compiles the FULL sharded train
step for the shipped `scripts/configs/oryx_7b_sft.json` (mesh dp=1
fsdp=16, 128-row optimizer step, the bench 2048-token mixed image+text
row composition) from ShapeDtypeStructs — no 7B params are ever
materialized — and reads the compiler's per-device memory analysis for
each (remat policy, moment dtype, grad accum) point.

Compiler target, in order of preference:
  * **TPU topology AOT** (default): `jax.experimental.topologies` with
    the local libtpu compiles for a REAL v5e:4x4 (16-chip) target with
    no chips attached — argument/temp bytes are the actual XLA:TPU
    buffer assignment, bf16 at true width.
  * CPU forced-16-device fallback (`AOT7B_PLATFORM=cpu`): portable, but
    XLA:CPU's float normalization widens every bf16 buffer to fp32, so
    temp bytes overstate the TPU footprint by roughly the bf16 share
    (measured: 15.8 GB CPU-temp vs 9.3 GB TPU-temp for the same
    attn/accum-8 program). Use only for policy DELTAS.

    python scripts/estimate_7b_mesh_memory.py [policy[:moment_dtype[:accum]] ...]

One JSON line per case:
  {"policy": ..., "moment_dtype": ..., "grad_accum_steps": ...,
   "args_gb": ..., "temp_gb": ..., "total_gb": ..., "state_gb_total": ...,
   "sharded_ok": true, "fits_16gb": ...}
and a final {"winner": ..., "table": [...]} summary line.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GB = 1024**3
N_DEV = 16
_CHILD_ENV = "ORYX_TPU_AOT7B_CHILD"
V5E_HBM_GB = 16.0

# The optimizer step covers the config's 128 global rows over 16 chips;
# grad accumulation splits it into microbatches (the scan in
# train/step.py), which is THE activation-memory lever at fixed global
# batch. Row composition mirrors the bench geometry (2048-token bucket,
# one 448px image per row -> 256 patches, 64 visual tokens at 4x).
ROWS_STEP = 128
SEQ = 2048
PATCHES_PER_IMG = 256
Q_PER_IMG = 64


def _devices():
    """16 compile-target devices: TPU topology (preferred) or forced CPU."""
    import numpy as np

    import jax

    if os.environ.get("AOT7B_PLATFORM") == "cpu":
        devs = jax.devices("cpu")
        if len(devs) < N_DEV:
            raise RuntimeError(
                f"need {N_DEV} CPU devices "
                f"(XLA_FLAGS=--xla_force_host_platform_device_count={N_DEV})"
            )
        return np.array(devs[:N_DEV]), "cpu_forced16"
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:4x4")
    return np.array(topo.devices), "tpu_v5e_4x4_topology"


def one(policy: str, moment_dtype: str = "float32", accum: int = 1) -> dict:
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.parallel import sharding
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer

    with open(os.path.join(REPO, "scripts/configs/oryx_7b_sft.json")) as f:
        cfg = cfg_lib.OryxConfig.from_dict(json.load(f))
    assert cfg.mesh.fsdp == N_DEV and cfg.mesh.num_devices == N_DEV
    cfg = dataclasses.replace(
        cfg,
        attn_impl="xla",  # topology AOT has no Pallas lowering context;
        # the xla path's residual/activation shapes match
        train=dataclasses.replace(
            cfg.train,
            remat=policy != "none",
            remat_policy=policy if policy != "none" else "block",
            moment_dtype=moment_dtype,
            grad_accum_steps=accum,
        ),
    )
    devs, target = _devices()
    mesh = jax.sharding.Mesh(
        devs.reshape(cfg.mesh.dp, cfg.mesh.fsdp, cfg.mesh.tp, cfg.mesh.sp),
        ("dp", "fsdp", "tp", "sp"),
    )

    params_shape = jax.eval_shape(
        lambda: oryx.init_params(cfg, jax.random.key(0))
    )
    tx = make_optimizer(cfg.train, params_shape)
    opt_shape = jax.eval_shape(tx.init, params_shape)
    pshard = sharding.param_shardings(mesh, params_shape, "fsdp")
    ospecs = sharding.opt_state_specs(opt_shape, params_shape, "fsdp")
    oshard = jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )

    def sds(shape_struct, shard):
        return jax.ShapeDtypeStruct(
            shape_struct.shape, shape_struct.dtype, sharding=shard
        )

    state_in = step_lib.TrainState(
        step=sds(
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
        ),
        params=jax.tree.map(sds, params_shape, pshard),
        opt_state=jax.tree.map(sds, opt_shape, oshard),
    )

    assert ROWS_STEP % accum == 0
    rows = ROWS_STEP // accum  # rows per microbatch (scan over accum)
    P = rows * PATCHES_PER_IMG
    Q = rows * Q_PER_IMG
    PS = jax.sharding.PartitionSpec

    def bsds(shape, dtype):
        # Packed visual buffers and batch rows shard over the data width
        # when divisible (the dryrun/train placement rule).
        spec = PS(None, ("dp", "fsdp")) if shape[1] % N_DEV == 0 else PS()
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=jax.sharding.NamedSharding(mesh, spec)
        )

    patch_dim = cfg.vision.patch_size**2 * 3
    batch = {
        "patches": bsds((accum, P, patch_dim), jnp.float32),
        "segment_ids": bsds((accum, P), jnp.int32),
        "pos_coords": bsds((accum, P, 2), jnp.float32),
        "region_ids": bsds((accum, P), jnp.int32),
        "q_region_ids": bsds((accum, Q), jnp.int32),
        "token_ids": bsds((accum, rows, SEQ), jnp.int32),
        "visual_idx": bsds((accum, rows, SEQ), jnp.int32),
        "is_visual": bsds((accum, rows, SEQ), jnp.bool_),
        "attn_mask": bsds((accum, rows, SEQ), jnp.int32),
        "positions": bsds((accum, rows, SEQ), jnp.int32),
        "labels": bsds((accum, rows, SEQ), jnp.int32),
    }

    jit_step = jax.jit(
        step_lib.train_step_fn,
        static_argnames=("cfg", "tx", "sharding_mode"),
        donate_argnames=("state",),
    )
    base = {
        "target": target,
        "policy": policy,
        "moment_dtype": moment_dtype,
        "grad_accum_steps": accum,
        "rows_per_chip_micro": rows // N_DEV,
    }
    try:
        with jax.sharding.set_mesh(mesh):
            compiled = jit_step.lower(
                state_in, batch, cfg=cfg, tx=tx, sharding_mode="fsdp"
            ).compile()
    except Exception as e:  # XLA:TPU enforces HBM at compile time:
        # RESOURCE_EXHAUSTED "Used X of Y hbm" IS the does-not-fit
        # verdict, with the exact required footprint in the message.
        msg = str(e)
        if "RESOURCE_EXHAUSTED" not in msg:
            raise
        m = re.search(r"Used ([\d.]+)G of ([\d.]+)G hbm", msg)
        return {
            **base,
            "oom": True,
            "total_gb": float(m.group(1)) if m else None,
            "hbm_gb": float(m.group(2)) if m else None,
            "sharded_ok": False,
            "fits_16gb": False,
        }
    ma = compiled.memory_analysis()

    param_bytes = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(params_shape)
    )
    opt_bytes = sum(
        int(np.prod(getattr(l, "shape", ()))) * l.dtype.itemsize
        for l in jax.tree.leaves(opt_shape)
        if hasattr(l, "dtype")
    )
    total_state = param_bytes + opt_bytes
    per_dev_args = ma.argument_size_in_bytes
    # ZeRO-3 proof: per-device args ~ state/16 — a replicated 152064x3584
    # embedding (2.2 GB + its moments) would blow the 5% tolerance.
    sharded_ok = (
        abs(per_dev_args - total_state / N_DEV) < 0.05 * total_state / N_DEV
    )
    total = (
        ma.argument_size_in_bytes + ma.temp_size_in_bytes
        + ma.output_size_in_bytes - ma.alias_size_in_bytes
    )
    return {
        **base,
        "params_b": round(param_bytes / 4 / 1e9, 2),
        "state_gb_total": round(total_state / GB, 1),
        "args_gb": round(per_dev_args / GB, 2),
        "temp_gb": round(ma.temp_size_in_bytes / GB, 2),
        "alias_gb": round(ma.alias_size_in_bytes / GB, 2),
        "total_gb": round(total / GB, 2),
        "sharded_ok": bool(sharded_ok),
        "fits_16gb": bool(total < V5E_HBM_GB * GB),
    }


def main() -> None:
    if os.environ.get(_CHILD_ENV) != "1":
        # Re-exec in a clean child: the caller's process may hold a
        # 1-chip TPU backend (axon) or an 8-device test platform. The
        # child's jax client is CPU; the TPU *compiler* target comes
        # from the topology API, not the client platform.
        env = dict(os.environ)
        env[_CHILD_ENV] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
        prior = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        env["XLA_FLAGS"] = " ".join(
            prior + [f"--xla_force_host_platform_device_count={N_DEV}"]
        )
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env, cwd=REPO,
        )
        sys.exit(proc.returncode)

    # Case syntax: policy[:moment_dtype[:accum]] (e.g. attn_o:bfloat16:4).
    # Default ladder: the accum=1 whole-step compile documents WHY grad
    # accumulation is required (temps blow 16 GB), then the remat ladder
    # at the config's production accum (fp32 moments after bf16 at equal
    # policy, so the winner rule below prefers fp32 when both fit).
    cases = [("attn", "float32", 1),
             ("block", "float32", 8), ("attn", "float32", 8),
             ("attn_qkv", "float32", 8), ("attn_o", "bfloat16", 8),
             ("attn_o", "float32", 8)]
    if len(sys.argv) > 1:
        def parse(p):
            bits = p.split(":")
            return (bits[0], bits[1] if len(bits) > 1 else "float32",
                    int(bits[2]) if len(bits) > 2 else 1)
        cases = [parse(p) for p in sys.argv[1:]]
    table = []
    for policy, mdt, accum in cases:
        rec = one(policy, mdt, accum)
        table.append(rec)
        print(json.dumps(rec), flush=True)
    fitting = [r for r in table if r["fits_16gb"] and r["sharded_ok"]]
    # Winner: the fitting policy that saves the most recompute — the
    # ladder is ordered cheapest-recompute-last (and fp32 moments after
    # bf16 at equal policy), so take the LAST fit.
    winner = fitting[-1] if fitting else None
    print(json.dumps({
        "winner": winner and (
            f"{winner['policy']}:{winner['moment_dtype']}"
            f":{winner['grad_accum_steps']}"
        ),
        "n_fitting": len(fitting),
        "table": [
            {k: r[k] for k in ("policy", "moment_dtype", "grad_accum_steps",
                               "total_gb", "fits_16gb", "sharded_ok")}
            for r in table
        ],
    }), flush=True)


if __name__ == "__main__":
    main()
