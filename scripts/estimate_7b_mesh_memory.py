"""AOT per-chip memory proof: an Oryx config's SFT step on its full mesh.

Answers SURVEY.md §7 hard part 5 ("does the 7B train state actually fit
a v5e-16?" — and the 34B/longvideo pod questions) without chips: lowers
+ compiles the FULL sharded train step for a shipped config JSON on its
own `mesh` block from ShapeDtypeStructs — no params are ever
materialized — and reads the compiler's per-device memory analysis for
each (remat policy, moment dtype, grad accum) point.

Defaults prove `scripts/configs/oryx_7b_sft.json` on a v5e-16; env
knobs generalize it:
  AOT_CONFIG      config JSON path (default scripts/configs/oryx_7b_sft.json);
                  the device count and mesh shape come from its `mesh`
  AOT_ROWS_STEP   rows per optimizer step (default 128)
  AOT_SEQ         token bucket per row (default 2048)
  AOT_FRAMES      0 (default) = one 448px image per row (256 patches,
                  64 visual tokens at 4x); N = N-frame video per row
                  (64 patches and 4 visual tokens per frame at 16x —
                  BASELINE config 5's long-video shape)
  AOT_MESH        "dp,fsdp,tp,sp" mesh override (same device count).
                  sp>1 switches attention to ring_flash (sequence
                  parallelism) — the long-video lever: a smaller data
                  width admits deeper grad accumulation, cutting
                  tokens/chip/microbatch below pure-FSDP's floor of
                  one full row per chip

Compiler target, in order of preference:
  * **TPU topology AOT** (default): `jax.experimental.topologies` with
    the local libtpu compiles for a REAL v5e target (4x4 for 16-chip
    meshes, 8x8 for 64, ...) with no chips attached — argument/temp
    bytes are the actual XLA:TPU buffer assignment, bf16 at true width,
    and the config's shipped attn_impl (Pallas lowers fine) compiles
    as-is.
  * CPU forced-N-device fallback (`AOT7B_PLATFORM=cpu`): portable, but
    the xla attention path substitutes (no Pallas on CPU) and XLA:CPU's
    float normalization widens every bf16 buffer to fp32 (measured:
    15.8 GB CPU-temp vs 9.3 GB TPU-temp for the same attn/accum-8
    program). Use only for policy DELTAS.

    python scripts/estimate_7b_mesh_memory.py [policy[:moment_dtype[:accum]] ...]

One JSON line per case:
  {"policy": ..., "moment_dtype": ..., "grad_accum_steps": ...,
   "args_gb": ..., "temp_gb": ..., "total_gb": ..., "state_gb_total": ...,
   "sharded_ok": true, "fits_16gb": ...}
and a final {"winner": ..., "table": [...]} summary line.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GB = 1024**3
_CHILD_ENV = "ORYX_TPU_AOT7B_CHILD"
V5E_HBM_GB = 16.0

CONFIG = os.environ.get("AOT_CONFIG", "scripts/configs/oryx_7b_sft.json")
# Grad accumulation splits the step's rows into microbatches (the scan
# in train/step.py) — THE activation-memory lever at fixed global batch.
ROWS_STEP = int(os.environ.get("AOT_ROWS_STEP", "128"))
SEQ = int(os.environ.get("AOT_SEQ", "2048"))
FRAMES = int(os.environ.get("AOT_FRAMES", "0"))
if FRAMES:
    # Long-video rows: FRAMES frames x 64 patches, 16x compression.
    PATCHES_PER_ROW, Q_PER_ROW = FRAMES * 64, FRAMES * 4
else:
    # One 448px image per row: 256 patches, 64 visual tokens at 4x.
    PATCHES_PER_ROW, Q_PER_ROW = 256, 64

_TOPO_BY_N = {16: "v5e:4x4", 32: "v5e:4x8", 64: "v5e:8x8",
              128: "v5e:8x16", 256: "v5e:16x16"}


def _devices(n_dev: int):
    """n compile-target devices: TPU topology (preferred) or forced CPU."""
    import numpy as np

    import jax

    if os.environ.get("AOT7B_PLATFORM") == "cpu":
        devs = jax.devices("cpu")
        if len(devs) < n_dev:
            raise RuntimeError(
                f"need {n_dev} CPU devices "
                f"(XLA_FLAGS=--xla_force_host_platform_device_count={n_dev})"
            )
        return np.array(devs[:n_dev]), f"cpu_forced{n_dev}"
    from jax.experimental import topologies

    if n_dev not in _TOPO_BY_N:
        raise ValueError(
            f"no v5e topology mapped for {n_dev} devices; supported: "
            f"{sorted(_TOPO_BY_N)} (or AOT7B_PLATFORM=cpu with a forced "
            f"device count)"
        )
    name = _TOPO_BY_N[n_dev]
    topo = topologies.get_topology_desc(platform="tpu", topology_name=name)
    return np.array(topo.devices), f"tpu_{name.replace(':', '_')}_topology"


def one(policy: str, moment_dtype: str = "float32", accum: int = 1) -> dict:
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.parallel import sharding
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer

    with open(os.path.join(REPO, CONFIG)) as f:
        cfg = cfg_lib.OryxConfig.from_dict(json.load(f))
    if os.environ.get("AOT_MESH"):
        dp, fsdp, tp, sp = map(int, os.environ["AOT_MESH"].split(","))
        cfg = dataclasses.replace(
            cfg,
            mesh=dataclasses.replace(cfg.mesh, dp=dp, fsdp=fsdp,
                                     tp=tp, sp=sp),
        )
    n_dev = cfg.mesh.num_devices
    # As-shipped attn impl on the TPU target (Pallas lowers in topology
    # compiles); the CPU fallback substitutes the xla path. Sequence
    # parallelism trains under ring attention (the dryrun's rule).
    if os.environ.get("AOT7B_PLATFORM") == "cpu":
        overrides_impl = {"attn_impl": "xla" if cfg.mesh.sp == 1
                          else "ring"}
    elif cfg.mesh.sp > 1 and not cfg.attn_impl.startswith("ring"):
        overrides_impl = {"attn_impl": "ring_flash"}
    else:
        overrides_impl = {}
    cfg = dataclasses.replace(
        cfg,
        **overrides_impl,
        train=dataclasses.replace(
            cfg.train,
            remat=policy != "none",
            remat_policy=policy if policy != "none" else "block",
            moment_dtype=moment_dtype,
            grad_accum_steps=accum,
        ),
    )
    devs, target = _devices(n_dev)
    mesh = jax.sharding.Mesh(
        devs.reshape(cfg.mesh.dp, cfg.mesh.fsdp, cfg.mesh.tp, cfg.mesh.sp),
        ("dp", "fsdp", "tp", "sp"),
    )

    params_shape = jax.eval_shape(
        lambda: oryx.init_params(cfg, jax.random.key(0))
    )
    tx = make_optimizer(cfg.train, params_shape)
    opt_shape = jax.eval_shape(tx.init, params_shape)
    pshard = sharding.param_shardings(mesh, params_shape, "fsdp")
    ospecs = sharding.opt_state_specs(opt_shape, params_shape, "fsdp")
    oshard = jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), ospecs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )

    def sds(shape_struct, shard):
        return jax.ShapeDtypeStruct(
            shape_struct.shape, shape_struct.dtype, sharding=shard
        )

    state_in = step_lib.TrainState(
        step=sds(
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
        ),
        params=jax.tree.map(sds, params_shape, pshard),
        opt_state=jax.tree.map(sds, opt_shape, oshard),
    )

    assert ROWS_STEP % accum == 0
    rows = ROWS_STEP // accum  # rows per microbatch (scan over accum)
    P = rows * PATCHES_PER_ROW
    Q = rows * Q_PER_ROW
    PS = jax.sharding.PartitionSpec
    data_width = cfg.mesh.dp * cfg.mesh.fsdp

    mesh_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    batch_bytes_per_chip = [0]

    def bsds(name, shape, dtype):
        # THE trainer placement rule (sharding.batch_field_spec, applied
        # by field name — a divisibility heuristic would let the row
        # axis leak onto sp at low accum): packed visual buffers shard
        # over the full (dp, fsdp, sp) width, token rows over the data
        # width; non-divisible axes replicate. Width derives from the
        # spec itself (the trainer's drift-proof form).
        spec = sharding.batch_field_spec(name)
        width = 1
        for ax in spec[1]:
            width *= mesh_sizes[ax]
        if shape[1] % width != 0:
            spec, width = PS(), 1
        batch_bytes_per_chip[0] += (
            int(np.prod(shape)) * jnp.dtype(dtype).itemsize // width
        )
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=jax.sharding.NamedSharding(mesh, spec)
        )

    patch_dim = cfg.vision.patch_size**2 * 3
    shapes = {
        "patches": ((accum, P, patch_dim), jnp.float32),
        "segment_ids": ((accum, P), jnp.int32),
        "pos_coords": ((accum, P, 2), jnp.float32),
        "region_ids": ((accum, P), jnp.int32),
        "q_region_ids": ((accum, Q), jnp.int32),
        "token_ids": ((accum, rows, SEQ), jnp.int32),
        "visual_idx": ((accum, rows, SEQ), jnp.int32),
        "is_visual": ((accum, rows, SEQ), jnp.bool_),
        "attn_mask": ((accum, rows, SEQ), jnp.int32),
        "positions": ((accum, rows, SEQ), jnp.int32),
        "labels": ((accum, rows, SEQ), jnp.int32),
    }
    batch = {k: bsds(k, s, d) for k, (s, d) in shapes.items()}

    jit_step = jax.jit(
        step_lib.train_step_fn,
        static_argnames=("cfg", "tx", "sharding_mode"),
        donate_argnames=("state",),
    )
    base = {
        "target": target,
        "policy": policy,
        "moment_dtype": moment_dtype,
        "grad_accum_steps": accum,
        "mesh": f"dp{cfg.mesh.dp}_fsdp{cfg.mesh.fsdp}"
                f"_tp{cfg.mesh.tp}_sp{cfg.mesh.sp}",
        "attn_impl": cfg.attn_impl,
        "tokens_per_chip_micro": rows * SEQ // n_dev,
    }
    try:
        with jax.sharding.set_mesh(mesh):
            compiled = jit_step.lower(
                state_in, batch, cfg=cfg, tx=tx, sharding_mode="fsdp"
            ).compile()
    except Exception as e:  # XLA:TPU enforces HBM at compile time:
        # RESOURCE_EXHAUSTED "Used X of Y hbm" IS the does-not-fit
        # verdict, with the exact required footprint in the message.
        msg = str(e)
        if "RESOURCE_EXHAUSTED" not in msg:
            raise
        m = re.search(r"Used ([\d.]+)G of ([\d.]+)G hbm", msg)
        return {
            **base,
            "oom": True,
            "total_gb": float(m.group(1)) if m else None,
            "hbm_gb": float(m.group(2)) if m else None,
            "sharded_ok": False,
            "fits_16gb": False,
        }
    ma = compiled.memory_analysis()

    param_bytes = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(params_shape)
    )
    opt_bytes = sum(
        int(np.prod(getattr(l, "shape", ()))) * l.dtype.itemsize
        for l in jax.tree.leaves(opt_shape)
        if hasattr(l, "dtype")
    )
    total_state = param_bytes + opt_bytes
    per_dev_args = ma.argument_size_in_bytes
    # ZeRO-3 proof: per-device args minus the batch's own per-chip
    # share ~ state/n — a replicated embedding (2.2 GB at Qwen2-7B
    # vocab, + its moments) would blow the 5% tolerance. At long-video
    # shapes the input buffers are GBs, so they must be accounted, not
    # assumed negligible.
    state_args = per_dev_args - batch_bytes_per_chip[0]
    sharded_ok = (
        abs(state_args - total_state / n_dev) < 0.05 * total_state / n_dev
    )
    total = (
        ma.argument_size_in_bytes + ma.temp_size_in_bytes
        + ma.output_size_in_bytes - ma.alias_size_in_bytes
    )
    return {
        **base,
        "params_b": round(param_bytes / 4 / 1e9, 2),
        "state_gb_total": round(total_state / GB, 1),
        "args_gb": round(per_dev_args / GB, 2),
        "temp_gb": round(ma.temp_size_in_bytes / GB, 2),
        "alias_gb": round(ma.alias_size_in_bytes / GB, 2),
        "total_gb": round(total / GB, 2),
        "sharded_ok": bool(sharded_ok),
        "fits_16gb": bool(total < V5E_HBM_GB * GB),
    }


def main() -> None:
    if os.environ.get(_CHILD_ENV) != "1":
        # Re-exec in a clean child: the caller's process may hold a
        # 1-chip TPU backend (axon) or an 8-device test platform. The
        # child's jax client is CPU; the TPU *compiler* target comes
        # from the topology API, not the client platform.
        env = dict(os.environ)
        env[_CHILD_ENV] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
        # Forced device count only matters for the CPU fallback; size it
        # from the config so any mesh width works.
        cfg_path = os.path.join(REPO, CONFIG)
        with open(cfg_path) as f:
            m = json.load(f).get("mesh", {})
        n_dev = 1
        for ax in ("dp", "fsdp", "tp", "sp"):
            n_dev *= int(m.get(ax, 1))
        prior = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        env["XLA_FLAGS"] = " ".join(
            prior + [f"--xla_force_host_platform_device_count={n_dev}"]
        )
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env, cwd=REPO,
        )
        sys.exit(proc.returncode)

    # Case syntax: policy[:moment_dtype[:accum]] (e.g. attn_o:bfloat16:4).
    # Default ladder: the accum=1 whole-step compile documents WHY grad
    # accumulation is required (temps blow 16 GB), then the remat ladder
    # at the config's production accum (fp32 moments after bf16 at equal
    # policy, so the winner rule below prefers fp32 when both fit).
    cases = [("attn", "float32", 1),
             ("block", "float32", 8), ("attn", "float32", 8),
             ("attn_qkv", "float32", 8), ("attn_o", "bfloat16", 8),
             ("attn_o", "float32", 8)]
    if len(sys.argv) > 1:
        def parse(p):
            bits = p.split(":")
            return (bits[0], bits[1] if len(bits) > 1 else "float32",
                    int(bits[2]) if len(bits) > 2 else 1)
        cases = [parse(p) for p in sys.argv[1:]]
    table = []
    for policy, mdt, accum in cases:
        rec = one(policy, mdt, accum)
        table.append(rec)
        print(json.dumps(rec), flush=True)
    fitting = [r for r in table if r["fits_16gb"] and r["sharded_ok"]]
    # Winner: the fitting policy that saves the most recompute — the
    # ladder is ordered cheapest-recompute-last (and fp32 moments after
    # bf16 at equal policy), so take the LAST fit.
    winner = fitting[-1] if fitting else None
    print(json.dumps({
        "winner": winner and (
            f"{winner['policy']}:{winner['moment_dtype']}"
            f":{winner['grad_accum_steps']}"
        ),
        "n_fitting": len(fitting),
        "table": [
            {k: r[k] for k in ("policy", "moment_dtype", "grad_accum_steps",
                               "total_gb", "fits_16gb", "sharded_ok")}
            for r in table
        ],
    }), flush=True)


if __name__ == "__main__":
    main()
