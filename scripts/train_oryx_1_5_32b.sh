#!/usr/bin/env bash
# Oryx-1.5-32B (Qwen2.5-32B backbone) SFT on a v5e-64 pod: fsdp=64 +
# grad accum. The reference's Oryx-1.5 series swaps the backbone to
# Qwen2.5 (7B/32B) with the same vision/compressor stack and training
# recipe (SURVEY.md §2b "ZeRO-3 for 34B/long-video" applies unchanged).
set -euo pipefail
cd "$(dirname "$0")/.."

DATA=${DATA:?path to conversation-records json}
TOKENIZER=${TOKENIZER:?path to Qwen2.5 tokenizer dir}
HF_LLM=${HF_LLM:-}
HF_VISION=${HF_VISION:-}

python -m oryx_tpu.train.cli \
  --config scripts/configs/oryx_1_5_32b_sft.json \
  --data "$DATA" \
  --tokenizer-path "$TOKENIZER" \
  ${HF_LLM:+--hf-llm "$HF_LLM"} \
  ${HF_VISION:+--hf-vision "$HF_VISION"} \
  --sharding fsdp \
  --metrics-path logs/oryx1_5_32b_metrics.jsonl \
  --output-dir models/oryx1_5_32b-sft \
  "$@"
