#!/usr/bin/env bash
# Benchmark evaluation (SURVEY.md §3.5): VideoMME-style MCQ tasks in the
# harness's task-json format. Multi-host: run on every host with
# PROCESS_INDEX/PROCESS_COUNT; merge per-process result jsons after.
set -euo pipefail
cd "$(dirname "$0")/.."

MODEL=${MODEL:?path to oryx_tpu model dir}
TASK=${TASK:?task .json/.jsonl/.csv file}

python -m oryx_tpu.eval.harness \
  --model-path "$MODEL" \
  --task "$TASK" \
  --process-index "${PROCESS_INDEX:-0}" \
  --process-count "${PROCESS_COUNT:-1}" \
  --output "results/$(basename "$TASK" .jsonl)_${PROCESS_INDEX:-0}.json" \
  "$@"
