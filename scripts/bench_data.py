"""Host data-pipeline throughput: the reference's DataLoader-floor
analog, measurable without a TPU (this is all host CPU work).

Times visual preprocessing (resize+normalize+patchify, the pipeline's
hot loop) through pack_raw_images on a 64-frame 224px video request —
native C++ path (native/loader.cpp thread pool) vs the pure-numpy
fallback, frames/sec. Prints one JSON line; numbers land in
TPU_VALIDATION.md.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPS = int(os.environ.get("DATA_REPS", "5"))
FRAMES = int(os.environ.get("DATA_FRAMES", "64"))


def _time(fn, reps=REPS):
    fn()  # warm caches / lazy builds
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.percentile(ts, 50))


def main() -> None:
    from oryx_tpu import config as cfg_lib
    from oryx_tpu.data import native_loader
    from oryx_tpu.ops import packing

    cfg = cfg_lib.oryx_tiny()
    rng = np.random.default_rng(0)
    frames = [
        rng.integers(0, 255, size=(224, 224, 3), dtype=np.uint8)
        for _ in range(FRAMES)
    ]

    def pack():
        packing.pack_raw_images(
            frames, patch_size=cfg.vision.patch_size,
            base_grid=cfg.vision.base_grid, side_factors=16,
        )

    # High-res ingest shape (4K video frame -> patch grid): the
    # downscale case where touching only the sampled taps matters.
    img4k = rng.integers(0, 255, size=(2160, 3840, 3), dtype=np.uint8)

    def pack4k():
        packing.pack_raw_images(
            [img4k] * 4, patch_size=cfg.vision.patch_size,
            base_grid=cfg.vision.base_grid, side_factors=16,
        )

    native_built = native_loader.build(quiet=True)
    results = {}
    if native_built and native_loader.is_available():
        results["native_frames_per_s"] = round(FRAMES / _time(pack), 1)
        results["native_4k_ms_per_frame"] = round(_time(pack4k) / 4 * 1e3, 1)
    os.environ["ORYX_NATIVE_LIB"] = "/nonexistent"  # force python fallback
    os.environ["ORYX_NATIVE_AUTOBUILD"] = "0"  # and skip the futile rebuild
    native_loader._lib = None
    native_loader._lib_failed = False
    results["python_frames_per_s"] = round(FRAMES / _time(pack), 1)
    results["python_4k_ms_per_frame"] = round(_time(pack4k) / 4 * 1e3, 1)
    if "native_frames_per_s" in results:
        results["native_speedup"] = round(
            results["native_frames_per_s"] / results["python_frames_per_s"], 2
        )
        results["native_4k_speedup"] = round(
            results["python_4k_ms_per_frame"]
            / results["native_4k_ms_per_frame"], 1
        )

    print(json.dumps({
        "metric": "host_pipeline_throughput",
        "frames": FRAMES,
        "reps": REPS,
        **results,
    }))


if __name__ == "__main__":
    main()
