"""CI well-formedness gate for the trainer telemetry exporter.

Runs a short (3-step, tiny-geometry) CPU train with `--metrics-port`
semantics (Trainer(metrics_port=0)) on a background thread and checks,
from OUTSIDE, what a Prometheus scraper + load balancer would see:

  * /readyz is 503 before the step loop starts and flips to 200 while
    it runs;
  * /metrics is the exact Prometheus content type, every family name
    carries the `oryx_train_` prefix (the shared `oryx_anomaly_` family
    is the one deliberate exception), no family is declared twice, and
    the acceptance series
    oryx_train_{loss,tokens_per_sec,mfu,goodput_ratio,hbm_live_bytes}
    are present with sane values;
  * /healthz answers 200.

Exit 0 = all good; nonzero prints what broke. Wired into
scripts/check_tier1.sh after the serving-endpoint gate.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REQUIRED = (
    "oryx_train_loss",
    "oryx_train_tokens_per_sec",
    "oryx_train_mfu",
    "oryx_train_goodput_ratio",
    "oryx_train_hbm_live_bytes",
)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def _get(port: int, path: str, *, raw: bool = False):
    """(status, parsed body) — 503 is a result, not an exception."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            body = r.read().decode()
            return r.status, (body if raw else json.loads(body)), dict(
                r.headers
            )
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode()), dict(e.headers)


def main() -> None:
    import numpy as np

    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.train.trainer import Trainer

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tests.test_trainer_modes import _batch

    cfg = dataclasses.replace(
        cfg_lib.oryx_tiny(),
        mesh=cfg_lib.MeshConfig(dp=2, fsdp=4, tp=1, sp=1),
        train=dataclasses.replace(
            cfg_lib.oryx_tiny().train,
            num_train_steps=3, log_every=1, checkpoint_every=100,
            checkpoint_dir="/tmp/oryx_train_telemetry_gate_ckpt",
        ),
    )
    trainer = Trainer(cfg, metrics_port=0)
    port = trainer.telemetry.port
    code, body, _ = _get(port, "/readyz")
    if code != 503 or body.get("ready") is not False:
        fail(f"/readyz before the step loop: want 503/ready=false, got "
             f"{code} {body}")
    code, body, _ = _get(port, "/healthz")
    if code != 200 or body != {"status": "ok"}:
        fail(f"/healthz: want 200 ok, got {code} {body}")

    host = _batch(cfg)
    done = threading.Event()
    errors: list[BaseException] = []

    def run():
        try:
            trainer.fit(
                iter([host] * 3), num_steps=3, resume=False, prefetch=0
            )
        except BaseException as e:  # surfaced below
            errors.append(e)
        finally:
            done.set()

    threading.Thread(target=run, daemon=True).start()

    # /readyz must flip to 200 while the loop runs (the first step's
    # compile dominates; poll generously).
    deadline = time.monotonic() + 240
    flipped = False
    while time.monotonic() < deadline:
        code, body, _ = _get(port, "/readyz")
        if code == 200 and body.get("ready") is True:
            flipped = True
            break
        if done.is_set():
            break
        time.sleep(0.5)
    if errors:
        raise errors[0]
    if not flipped:
        fail("/readyz never flipped to 200 during the run")
    done.wait(timeout=240)

    code, text, headers = _get(port, "/metrics", raw=True)
    if code != 200:
        fail(f"/metrics returned {code}")
    if headers.get("Content-Type") != "text/plain; version=0.0.4":
        fail(f"/metrics content type {headers.get('Content-Type')!r}, "
             "want the Prometheus text exposition type")

    families: set[str] = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            name = line.split()[2]
            if name in families:
                fail(f"duplicate metric family {name!r}")
            families.add(name)
            continue
        if line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][\w:]*)(\{[^}]*\})? (\S+)$", line)
        if not m:
            fail(f"malformed sample line: {line!r}")
        if not m.group(1).startswith(("oryx_train_", "oryx_anomaly_")):
            fail(f"unprefixed metric name: {line!r}")
    for want in REQUIRED:
        if want not in families:
            fail(f"required series {want} missing from /metrics "
                 f"(families: {sorted(f for f in families if 'train' in f)})")
    # 3 steps really happened and the accounting is sane.
    sample = {}
    for line in text.splitlines():
        if line and not line.startswith("#") and "{" not in line:
            k, v = line.rsplit(" ", 1)
            sample[k] = float(v)
    if sample.get("oryx_train_steps_total") != 3:
        fail(f"steps_total != 3: {sample.get('oryx_train_steps_total')}")
    if not np.isfinite(sample.get("oryx_train_loss", float("nan"))):
        fail(f"non-finite loss gauge: {sample.get('oryx_train_loss')}")
    if not 0 < sample.get("oryx_train_goodput_ratio", 0) <= 1:
        fail(f"goodput_ratio out of range: "
             f"{sample.get('oryx_train_goodput_ratio')}")

    trainer.close()
    code, _, _ = _get_or_dead(port)
    print("train telemetry OK: /readyz 503->200, /metrics "
          f"({len(families)} families, oryx_train_ prefixed, "
          "no duplicates, acceptance series present), /healthz 200")


def _get_or_dead(port: int):
    """After close() the exporter should stop answering; tolerate
    either a refused connection or a last in-flight response."""
    try:
        return _get(port, "/healthz")
    except OSError:
        return None, None, None


if __name__ == "__main__":
    main()
