#!/usr/bin/env bash
# Watch the axon tunnel and run the on-chip agenda the moment it is up.
#
#   scripts/tunnel_watch.sh [OUT_DIR] [DEADLINE_HOURS]
#
# Probes the default backend in a short-lived subprocess every ~9 min;
# on a green probe, runs scripts/tpu_round4.sh "$OUT_DIR". Keeps
# retrying (the tunnel can die mid-agenda; tpu_round4.sh is itself
# hang-proof and continue-on-failure) until the agenda exits 0 or the
# deadline passes. Designed to be left running in the background for
# hours — the tunnel's outages are long and its recoveries unannounced.
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
OUT=${1:-/tmp/r4_onchip}
DEADLINE_H=${2:-10}
PROBE='import jax, jax.numpy as jnp; v = float(jax.device_get(jnp.sum(jnp.ones((256, 256), jnp.float32)))); assert v == 65536.0, v; print("PROBE_OK", jax.default_backend(), flush=True)'
end=$(( $(date +%s) + DEADLINE_H * 3600 ))
try=0
while [ "$(date +%s)" -lt "$end" ]; do
  try=$((try + 1))
  if timeout --kill-after=15 120 python -c "$PROBE" >/dev/null 2>&1; then
    echo "[$(date -u +%H:%M:%S)] probe $try ok — running agenda" >&2
    if bash scripts/tpu_round4.sh "$OUT"; then
      echo "[$(date -u +%H:%M:%S)] agenda complete" >&2
      exit 0
    fi
    echo "[$(date -u +%H:%M:%S)] agenda incomplete (rc!=0); will retry" >&2
  else
    echo "[$(date -u +%H:%M:%S)] probe $try failed (tunnel down)" >&2
  fi
  sleep 540
done
echo "deadline reached without a complete agenda" >&2
exit 1
