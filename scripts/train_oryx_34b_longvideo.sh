#!/usr/bin/env bash
# 34B long-video SFT on a v5e-64 (BASELINE config 5: 256-frame records,
# ZeRO-3 at pod scale): ring attention over sp=4 with the ZeRO state
# sharded over the COMBINED fsdp x sp width, vision patch shards riding
# sp, bf16 moments, block remat, grad_accum 8 — the configuration the
# real XLA:TPU compiler proves fits 16 GB/chip (14.71 GB,
# TPU_VALIDATION.md round 5; scripts/estimate_7b_mesh_memory.py with
# AOT_CONFIG=scripts/configs/oryx_34b_longvideo.json AOT_FRAMES=256).
set -euo pipefail
cd "$(dirname "$0")/.."

DATA=${DATA:?path to conversation-records json}
TOKENIZER=${TOKENIZER:?path to Yi/Qwen tokenizer dir}

python -m oryx_tpu.train.cli \
  --config scripts/configs/oryx_34b_longvideo.json \
  --data "$DATA" \
  --tokenizer-path "$TOKENIZER" \
  --template yi_34b \
  --video-frames 256 \
  --sharding fsdp \
  --metrics-path logs/oryx34b_video_metrics.jsonl \
  --output-dir models/oryx34b-longvideo \
  "$@"
