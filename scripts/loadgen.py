#!/usr/bin/env python
"""Open-loop load/SLO capacity harness for the serving stack.

Drives a live server at a controlled OFFERED load — seeded Poisson
arrivals that do NOT wait for completions (open loop: a saturated
server keeps receiving work at the offered rate, exactly the regime
where closed-loop benchmarks lie) — across a sweep of rates, and
reports what capacity actually is:

  * client-measured p50/p95/p99 TTFT and per-token latency per stage
    (streaming SSE requests; TTFT = first content delta);
  * goodput: tokens/s from requests that completed WITHIN the SLO,
    vs offered load — the curve whose flattening is saturation;
  * the saturation knee: the highest offered load whose stage still
    met the SLO for >= --knee-good-frac of its requests (every stage
    past it is saturated);
  * error breakdown (429 backpressure / 503 unavailable / 504
    deadline / transport);
  * per-stage deltas of the server's own SLO anomaly detectors
    (oryx_anomaly_total{kind="ttft_slo"|"queue_depth_slo"}) — the
    pass/fail gate: ZERO firings at or below the knee;
  * per-request cost attribution from the scheduler's ledger (final
    SSE metadata): prefill vs prefix-cache-spliced tokens, decode
    steps, and page-seconds (pages-held x time, the HBM currency).

Workload shape: prompt and output lengths are drawn per-request from
small mixed distributions, and --shared-prefix-frac of requests carry
one of --shared-prefix-count long shared system prompts so the sweep
exercises the TokenTrie prefix cache like real traffic does.

Everything client-side is stdlib (urllib + threading + random); the
histogram math comes from the shared helpers in oryx_tpu.utils.metrics
(the same bucket interpolation scripts/check_serving_endpoints.py
uses).

    # against a live server
    python scripts/loadgen.py --base-url http://127.0.0.1:8000 \
        --rates 1,2,4,8,16 --duration 30 --slo-ttft 2.0 --gate

    # CI smoke: boots a tiny CPU server in-process, short sweep,
    # SLO-detector gate + report schema check + cost-ledger audit
    JAX_PLATFORMS=cpu python scripts/loadgen.py --smoke

Writes BENCH_loadgen.json (see docs/OBSERVABILITY.md "Capacity & load
testing" for how to read the knee and the goodput curve).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

ANOMALY_KINDS = (
    "ttft_slo", "queue_depth_slo",
    # Output-quality sentinels (ISSUE 14): zero firings at/below the
    # knee is part of the gate — a drifting audit or collapsing accept
    # rate under healthy load is a correctness regression, not noise.
    "audit_drift", "spec_accept_collapse",
)

WORDS = (
    "capacity goodput latency saturation paged prefill decode cache "
    "page token slot queue chunk splice replay admit evict serve"
).split()


# ---------------------------------------------------------------------------
# Workload synthesis (all draws from one seeded Random -> the schedule
# and every request body are reproducible)
# ---------------------------------------------------------------------------


def poisson_arrivals(rng: random.Random, rate: float,
                     duration: float) -> list[float]:
    """Open-loop arrival offsets in [0, duration): exponential
    inter-arrival times at `rate` req/s. Always at least one arrival
    (a stage that sends nothing measures nothing)."""
    out: list[float] = []
    t = rng.expovariate(rate)
    while t < duration:
        out.append(t)
        t += rng.expovariate(rate)
    return out or [0.0]


def filler_text(rng: random.Random, chars: int) -> str:
    words = []
    n = 0
    while n < chars:
        w = rng.choice(WORDS)
        words.append(w)
        n += len(w) + 1
    return " ".join(words)[:chars]


def build_body(rng: random.Random, cfg: dict) -> dict:
    """One request body: sampled prompt/output lengths, a shared
    system prefix with probability shared_prefix_frac (exercises the
    prefix cache), streaming with usage so the client can count tokens
    and read the final cost metadata."""
    messages = []
    if cfg["shared_prefixes"] and rng.random() < cfg["shared_prefix_frac"]:
        messages.append({
            "role": "system",
            "content": rng.choice(cfg["shared_prefixes"]),
        })
    chars = rng.choice(cfg["prompt_chars_choices"])
    messages.append({
        "role": "user",
        "content": f"q{rng.randrange(1_000_000)}: "
                   + filler_text(rng, chars),
    })
    return {
        "messages": messages,
        "max_tokens": rng.choice(cfg["max_tokens_choices"]),
        "stream": True,
        "stream_options": {"include_usage": True},
    }


# ---------------------------------------------------------------------------
# SSE client
# ---------------------------------------------------------------------------


def send_stream(base: str, body: dict, timeout: float) -> dict:
    """POST one streaming completion; returns the client-side record:
    status, ttft_s (first content delta), per_token_s, completion
    token count (from the usage chunk), the server's cost ledger
    (from the final chunk's "oryx" metadata) and an error class."""
    rec: dict = {
        "status": None, "ok": False, "ttft_s": None, "per_token_s": None,
        "e2e_s": None, "tokens": 0, "cost": None, "error": None,
        # Router-mode attribution (zero/absent against a bare replica):
        # how many times the router retried this request onto another
        # replica, which replica finally served it, and whether an
        # error was ROUTER-generated (no healthy replica / draining)
        # rather than a backend's own answer.
        "router_retries": 0, "replica": None,
    }
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        base + "/v1/chat/completions", data=data,
        headers={"Content-Type": "application/json"},
    )
    t0 = time.monotonic()
    t_first = t_last = None
    finished = False
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            rec["status"] = r.status
            rec["router_retries"] = int(
                r.headers.get("X-Oryx-Router-Retries") or 0
            )
            rec["replica"] = r.headers.get("X-Oryx-Router-Replica")
            for raw in r:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    break
                obj = json.loads(payload)
                if "error" in obj:
                    rec["error"] = "stream_error"
                    break
                now = time.monotonic()
                choices = obj.get("choices") or []
                if choices:
                    if choices[0].get("delta", {}).get("content"):
                        if t_first is None:
                            t_first = now
                            rec["ttft_s"] = now - t0
                        t_last = now
                    if choices[0].get("finish_reason"):
                        finished = True
                if obj.get("usage"):
                    rec["tokens"] = int(
                        obj["usage"].get("completion_tokens", 0)
                    )
                if isinstance(obj.get("oryx"), dict):
                    rec["cost"] = obj["oryx"].get("cost")
    except urllib.error.HTTPError as e:
        rec["status"] = e.code
        hdrs = e.headers or {}
        rec["router_retries"] = int(
            hdrs.get("X-Oryx-Router-Retries") or 0
        )
        # A 503 the ROUTER generated (fleet exhausted / router drain)
        # is a different incident from a backend's own 503 forwarded
        # through — the X-Oryx-Router-Error tag splits them.
        if e.code == 503 and hdrs.get("X-Oryx-Router-Error"):
            rec["error"] = "router_503"
        else:
            rec["error"] = str(e.code)
        e.close()
        rec["e2e_s"] = time.monotonic() - t0
        return rec
    except Exception:
        rec["error"] = "transport"
        rec["e2e_s"] = time.monotonic() - t0
        return rec
    rec["e2e_s"] = time.monotonic() - t0
    rec["ok"] = rec["error"] is None and finished
    if (
        rec["ok"] and rec["tokens"] > 1
        and t_first is not None and t_last is not None and t_last > t_first
    ):
        rec["per_token_s"] = (t_last - t_first) / (rec["tokens"] - 1)
    return rec


# ---------------------------------------------------------------------------
# Server-side scrapes
# ---------------------------------------------------------------------------


def scrape_metrics(base: str, timeout: float = 30.0) -> str:
    with urllib.request.urlopen(base + "/metrics", timeout=timeout) as r:
        return r.read().decode()


def build_info_labels(text: str, family: str) -> dict[str, str]:
    """Labels of an info gauge (build_info) from a text exposition —
    the target's self-declared identity (engine, revision, replica),
    stamped into the report so scripts/bench_compare.py can refuse
    cross-config comparisons instead of producing a noisy diff."""
    m = re.search(rf"^{re.escape(family)}\{{([^}}]*)\}} 1$", text, re.M)
    if not m:
        return {}
    return dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))


def fetch_timeline(base: str, n: int = 24, timeout: float = 30.0) -> dict:
    """One replica's /debug/timeline snapshot (utils/timeline.py): the
    per-stage flight-data-recorder embed — reading the records at the
    knee stage replaces guessing engine state from counter deltas. A
    target without the endpoint (window engine, old server) degrades
    to an error entry, never a failed stage."""
    try:
        with urllib.request.urlopen(
            base + f"/debug/timeline?n={n}", timeout=timeout
        ) as r:
            body = json.load(r)
        return {
            "total_steps": body.get("total_steps"),
            "counts_by_kind": body.get("counts_by_kind"),
            "records": body.get("records"),
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def fetch_pages_summary(base: str, timeout: float = 30.0) -> dict:
    """One target's /debug/pages?format=summary body (the page-pool
    observatory). Targets without the endpoint (window engine, old
    server) degrade to an error entry, never a failed stage."""
    try:
        with urllib.request.urlopen(
            base + "/debug/pages?format=summary", timeout=timeout
        ) as r:
            return json.load(r)
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _kind_counter_values(text: str, family: str) -> dict[str, float]:
    """{kind: value} of a kind-labeled counter family in a text
    exposition (the oryx_device_time_seconds_total shape)."""
    out: dict[str, float] = {}
    for m in re.finditer(
        rf'^{re.escape(family)}\{{kind="([^"]+)"\}} ([0-9.eE+-]+)$',
        text, re.M,
    ):
        out[m.group(1)] = float(m.group(2))
    return out


def memory_block(m0: str, m1: str, pages: dict,
                 timeline: dict) -> dict:
    """One stage's `memory` record: pool geometry + end-of-stage
    occupancy/fragmentation (from the observatory summary), peak
    occupancy over the stage (min free_pages across the stage's
    timeline records, floored by the boot-wide watermark), page
    lifetime/idle quantiles from the oryx_page_lifetime_seconds
    histogram DELTA across the stage, and the sampled device-time
    split (per-kind busy seconds vs the sampled wall window —
    busy <= wall per kind by construction, the gate's sanity bar).
    This block is what ROADMAP item 3's memory-economics PR will gate
    its halving claim on (scripts/bench_compare.py memory class)."""
    from oryx_tpu.utils.metrics import histogram_quantile, \
        parse_prom_histogram

    summary = pages.get("summary") or {}
    block: dict = {
        "pool": {
            "num_pages": pages.get("num_pages"),
            "page_size": pages.get("page_size"),
            "kv_dtype": pages.get("kv_dtype"),
            "kv_pool_bytes": pages.get("kv_pool_bytes"),
        },
        "end": {
            k: summary.get(k)
            for k in ("free", "slot", "cache", "shared",
                      "fragmentation_ratio", "reconciled")
        },
        "peak_pages_in_use": summary.get("peak_pages_in_use"),
    }
    if "error" in pages:
        block["error"] = pages["error"]
    num_pages = pages.get("num_pages")
    frees = [
        rec.get("free_pages")
        for rec in (timeline.get("records") or [])
        if isinstance(rec, dict) and rec.get("free_pages") is not None
    ]
    if num_pages is not None and frees:
        block["stage_peak_pages_in_use"] = num_pages - min(frees)
    kv_bytes = pages.get("kv_pool_bytes")
    peak = block.get(
        "stage_peak_pages_in_use", block.get("peak_pages_in_use")
    )
    if kv_bytes and num_pages and peak is not None:
        # Peak occupancy in HBM BYTES: pages x (pool bytes / pages) —
        # the row that halves under --kv-dtype int8 while the page
        # count stays put (pages are token-granular).
        block["stage_peak_kv_bytes"] = int(peak * kv_bytes / num_pages)
    for name, fam in (
        ("page_lifetime_s", "oryx_page_lifetime_seconds"),
        ("page_idle_s", "oryx_page_idle_seconds"),
    ):
        h0 = parse_prom_histogram(m0, fam)
        h1 = parse_prom_histogram(m1, fam)
        if h0 is None or h1 is None or h0[0] != h1[0]:
            block[name] = {"count": 0, "p50": None, "p95": None}
            continue
        counts = [b - a for a, b in zip(h0[1], h1[1])]
        total = h1[2] - h0[2]
        q = {}
        for p in (0.5, 0.95):
            v = histogram_quantile(p, h1[0], counts, total)
            q[f"p{int(p * 100)}"] = None if v != v else round(v, 6)
        block[name] = {"count": total, **q}
    dev0 = _kind_counter_values(m0, "oryx_device_time_seconds_total")
    dev1 = _kind_counter_values(m1, "oryx_device_time_seconds_total")
    wall0 = _kind_counter_values(
        m0, "oryx_profile_sampled_wall_seconds_total"
    )
    wall1 = _kind_counter_values(
        m1, "oryx_profile_sampled_wall_seconds_total"
    )
    block["device_time_s"] = {
        k: round(dev1[k] - dev0.get(k, 0.0), 6) for k in sorted(dev1)
    }
    block["sampled_wall_s"] = {
        k: round(wall1[k] - wall0.get(k, 0.0), 6) for k in sorted(wall1)
    }
    # Host-tier rows (the prefix cache's host-RAM spill plane): end-of
    # -stage residency plus the stage's reload economics — hits are
    # requests whose splice crossed into spilled blocks, uploads the
    # pages brought back. hit rate = uploaded pages per hit (how much
    # spilled prefix each hit recovered on average is uploads/hits;
    # the fraction of hits that recovered ANYTHING device-side is what
    # the closed-loop gate asserts via the counters themselves).
    rh = _counter_value(m1, "oryx_cache_reload_hit_total") \
        - _counter_value(m0, "oryx_cache_reload_hit_total")
    ru = _counter_value(m1, "oryx_cache_reload_upload_total") \
        - _counter_value(m0, "oryx_cache_reload_upload_total")
    block["host_tier"] = {
        "spilled_pages": _counter_value(m1, "oryx_cache_spilled_pages"),
        "host_bytes": _counter_value(m1, "oryx_cache_host_bytes"),
        "reload_hits": rh,
        "reload_uploads": ru,
        "reload_pages_per_hit": round(ru / rh, 4) if rh else None,
    }
    return block


def anomaly_counts(text: str) -> dict[str, float]:
    out = {}
    for kind in ANOMALY_KINDS:
        m = re.search(
            rf'^oryx_anomaly_total\{{kind="{kind}"\}} ([0-9.e+-]+)$',
            text, re.M,
        )
        out[kind] = float(m.group(1)) if m else 0.0
    return out


def server_hist_quantiles(
    m0: str, m1: str, family: str, qs: tuple[float, ...] = (0.5, 0.99)
) -> dict[str, float | None]:
    """Windowed quantiles of a server histogram across one stage: the
    element-wise DELTA of two cumulative scrapes is itself a valid
    cumulative histogram, fed to the shared bucket-interpolation
    helper."""
    from oryx_tpu.utils.metrics import histogram_quantile, \
        parse_prom_histogram

    h0, h1 = parse_prom_histogram(m0, family), parse_prom_histogram(m1, family)
    out: dict[str, float | None] = {}
    if h0 is None or h1 is None or h0[0] != h1[0]:
        return {f"p{int(q * 100)}": None for q in qs}
    bounds = h1[0]
    counts = [b - a for a, b in zip(h0[1], h1[1])]
    total = h1[2] - h0[2]
    for q in qs:
        v = histogram_quantile(q, bounds, counts, total)
        out[f"p{int(q * 100)}"] = None if v != v else round(v, 6)
    return out


def speculation_block(scrape_pairs: list[tuple[str, str]]) -> dict:
    """Per-stage speculation report from server scrape deltas (one
    (before, after) pair per backend; a fleet sums across replicas):
    accepted-tokens-per-step MEAN from the
    oryx_serving_accepted_tokens_per_step histogram's sum/count delta
    (the docs/OBSERVABILITY.md headline — >1 means speculation is
    converting drafts into latency), plus the raw draft economics.
    `active` stays False (and the mean None) on a non-speculative
    engine, so the block is schema-stable either way."""
    from oryx_tpu.utils.metrics import parse_prom_histogram

    fam = "oryx_serving_accepted_tokens_per_step"
    d_sum = d_cnt = prop = acc = 0.0
    for m0, m1 in scrape_pairs:
        h0 = parse_prom_histogram(m0, fam)
        h1 = parse_prom_histogram(m1, fam)
        if h0 is not None and h1 is not None:
            d_sum += h1[3] - h0[3]
            d_cnt += h1[2] - h0[2]
        for name, ref in (
            ("oryx_serving_draft_proposed_total", "prop"),
            ("oryx_serving_draft_accepted_total", "acc"),
        ):
            d = _counter_value(m1, name) - _counter_value(m0, name)
            if ref == "prop":
                prop += d
            else:
                acc += d
    return {
        "active": d_cnt > 0,
        "accepted_tokens_per_step": (
            round(d_sum / d_cnt, 4) if d_cnt > 0 else None
        ),
        "draft_proposed": prop,
        "draft_accepted": acc,
        "draft_accept_rate": round(acc / prop, 4) if prop > 0 else None,
    }


def audit_block(scrape_pairs: list[tuple[str, str]]) -> dict:
    """Per-stage output-audit report from server scrape deltas (one
    (before, after) pair per backend; a fleet sums across replicas):
    sampled/pass/drift/fail counts from oryx_audit_total{verdict=} and
    the derived pass_rate — bench_compare treats it as an EXACT-class
    metric (any non-pass on the fp path is a regression, not noise).
    Schema-stable with auditing off: zero counts, pass_rate None."""

    def verdict_value(text: str, verdict: str) -> float:
        m = re.search(
            rf'^oryx_audit_total\{{verdict="{verdict}"\}} '
            rf"([0-9.eE+-]+)$", text, re.M,
        )
        return float(m.group(1)) if m else 0.0

    out = {"sampled": 0.0, "pass": 0.0, "drift": 0.0, "fail": 0.0}
    for m0, m1 in scrape_pairs:
        out["sampled"] += (
            _counter_value(m1, "oryx_audit_sampled_total")
            - _counter_value(m0, "oryx_audit_sampled_total")
        )
        for v in ("pass", "drift", "fail"):
            out[v] += verdict_value(m1, v) - verdict_value(m0, v)
    done = out["pass"] + out["drift"] + out["fail"]
    out["pass_rate"] = round(out["pass"] / done, 4) if done else None
    return out


# ---------------------------------------------------------------------------
# Stage runner + aggregation
# ---------------------------------------------------------------------------


def _dist(values: list[float]) -> dict:
    from oryx_tpu.utils.metrics import sample_quantile

    if not values:
        return {"n": 0, "p50": None, "p95": None, "p99": None,
                "mean": None, "max": None}
    return {
        "n": len(values),
        "p50": round(sample_quantile(values, 0.5), 6),
        "p95": round(sample_quantile(values, 0.95), 6),
        "p99": round(sample_quantile(values, 0.99), 6),
        "mean": round(sum(values) / len(values), 6),
        "max": round(max(values), 6),
    }


def _counter_value(text: str, family: str) -> float:
    """Value of one unlabeled counter/gauge/sum sample, 0 if absent."""
    m = re.search(
        rf"^{re.escape(family)} ([0-9.eE+-]+)$", text, re.M
    )
    return float(m.group(1)) if m else 0.0


def replica_stage_split(r0: dict[str, str],
                        r1: dict[str, str]) -> dict[str, dict]:
    """Per-replica goodput attribution for one stage: the delta of
    each replica's own counters between the stage's two direct
    scrapes — completions served, prefix-cache hit tokens (the
    affinity payoff), and decode steps (the request_decode_steps
    histogram's sum, the device-work share)."""
    out: dict[str, dict] = {}
    total_completed = 0.0
    for rid in sorted(r1):
        completed = (
            _counter_value(r1[rid], "oryx_serving_completed")
            - _counter_value(r0.get(rid, ""), "oryx_serving_completed")
        )
        out[rid] = {
            "completed": completed,
            "prefix_hit_tokens": (
                _counter_value(
                    r1[rid], "oryx_serving_prefix_cache_hit_tokens_total"
                ) - _counter_value(
                    r0.get(rid, ""),
                    "oryx_serving_prefix_cache_hit_tokens_total",
                )
            ),
            "decode_steps": (
                _counter_value(
                    r1[rid], "oryx_serving_request_decode_steps_sum"
                ) - _counter_value(
                    r0.get(rid, ""),
                    "oryx_serving_request_decode_steps_sum",
                )
            ),
        }
        total_completed += completed
    for rid, row in out.items():
        row["completed_share"] = round(
            row["completed"] / total_completed, 4
        ) if total_completed > 0 else None
    return out


def aggregate_stage(rate: float, duration: float, results: list[dict],
                    hung: int, m0: str, m1: str, slo_ttft: float,
                    slo_per_token: float | None,
                    replica_scrapes: tuple[dict, dict] | None = None,
                    router: bool = False) -> dict:
    """One stage's record for the report. Goodput divides by the
    ARRIVAL window (`duration`), not the drain: open-loop capacity is
    tokens served per second of offered-load time. A hung request
    (worker still blocked past the drain, so it never appended a
    record) counts in `sent` and against `slo_good_frac` — offered
    traffic that never completed is the OPPOSITE of healthy and must
    not inflate the fraction the knee is found on."""
    ok = [r for r in results if r["ok"]]
    good = [
        r for r in ok
        if r["ttft_s"] is not None and r["ttft_s"] <= slo_ttft
        and (
            slo_per_token is None or r["per_token_s"] is None
            or r["per_token_s"] <= slo_per_token
        )
    ]
    errors = {"429": 0, "503": 0, "504": 0, "other_http": 0,
              "transport": 0, "stream_error": 0,
              "harness_inflight_cap": 0, "router_503": 0}
    for r in results:
        e = r["error"]
        if e is None:
            continue
        if e in ("429", "503", "504", "router_503"):
            # router_503 = the ROUTER answered (no healthy replica /
            # router drain), distinct from a backend 503 forwarded
            # through — conflating them would blame backends for a
            # routing-tier outage.
            errors[e] += 1
        elif e in ("transport", "stream_error", "harness_inflight_cap"):
            # harness_inflight_cap is a HARNESS-side shed, not a
            # server response — bucketing it as HTTP would blame the
            # server for load the generator never sent.
            errors[e] += 1
        else:
            errors["other_http"] += 1
    if replica_scrapes is not None:
        # Router target: the SLO detectors live on the replicas, not
        # the router — the stage's anomaly delta is the fleet sum of
        # each replica's own scrape pair.
        r0s, r1s = replica_scrapes
        anomalies = {
            k: sum(
                anomaly_counts(r1s[rid]).get(k, 0.0)
                - anomaly_counts(r0s.get(rid, "")).get(k, 0.0)
                for rid in r1s
            )
            for k in ANOMALY_KINDS
        }
    else:
        a0, a1 = anomaly_counts(m0), anomaly_counts(m1)
        anomalies = {k: a1[k] - a0.get(k, 0.0) for k in ANOMALY_KINDS}
    costs = [r["cost"] for r in results if r["cost"]]
    prefill = sum(c["prefill_tokens"] for c in costs)
    cached = sum(c["cached_tokens"] for c in costs)
    page_s = sum(c["page_seconds"] for c in costs)
    goodput = sum(r["tokens"] for r in good) / duration
    sent = len(results) + hung
    router_block = None
    if router:
        # Affinity across THIS stage: the delta of the router's own
        # hit/miss counters between its two scrapes.
        d_hits = (
            _counter_value(m1, "oryx_router_affinity_hits_total")
            - _counter_value(m0, "oryx_router_affinity_hits_total")
        )
        d_miss = (
            _counter_value(m1, "oryx_router_affinity_misses_total")
            - _counter_value(m0, "oryx_router_affinity_misses_total")
        )
        router_block = {
            "retries": sum(r.get("router_retries") or 0 for r in results),
            "router_503": errors["router_503"],
            "affinity": {
                "hits": d_hits,
                "misses": d_miss,
                "hit_rate": round(d_hits / (d_hits + d_miss), 4)
                if d_hits + d_miss > 0 else None,
            },
            "per_replica": replica_stage_split(*replica_scrapes)
            if replica_scrapes is not None else {},
        }
    out = {
        "offered_rps": rate,
        "sent": sent,
        "ok": len(ok),
        "good": len(good),
        "hung": hung,
        "slo_good_frac": round(len(good) / max(1, sent), 4),
        "goodput_tps": round(goodput, 3),
        "completed_tps": round(
            sum(r["tokens"] for r in ok) / duration, 3
        ),
        "ttft_s": _dist([
            r["ttft_s"] for r in results if r["ttft_s"] is not None
        ]),
        "per_token_s": _dist([
            r["per_token_s"] for r in results
            if r["per_token_s"] is not None
        ]),
        "server_ttft_s": server_hist_quantiles(
            m0, m1,
            "oryx_router_upstream_ttfb_seconds" if router
            else "oryx_serving_ttft_seconds",
        ),
        "errors": errors,
        "anomalies": anomalies,
        "speculation": speculation_block(
            [(replica_scrapes[0].get(rid, ""), replica_scrapes[1][rid])
             for rid in replica_scrapes[1]]
            if replica_scrapes is not None else [(m0, m1)]
        ),
        "audit": audit_block(
            [(replica_scrapes[0].get(rid, ""), replica_scrapes[1][rid])
             for rid in replica_scrapes[1]]
            if replica_scrapes is not None else [(m0, m1)]
        ),
        "cost": {
            "requests_with_cost": len(costs),
            "prefill_tokens": prefill,
            "cached_tokens": cached,
            "cache_hit_frac": round(
                cached / max(1, prefill + cached), 4
            ),
            "decode_steps": sum(c["decode_steps"] for c in costs),
            "decode_tokens": sum(
                c.get("decode_tokens", 0) for c in costs
            ),
            "page_seconds": round(page_s, 3),
            "mean_page_seconds": round(page_s / max(1, len(costs)), 6),
            "goodput_tokens_per_page_second": round(
                goodput * duration / page_s, 3
            ) if page_s > 0 else None,
        },
    }
    if router_block is not None:
        out["router"] = router_block
    return out


def run_stage(base: str, rate: float, cfg: dict,
              rng: random.Random,
              carryover: list | None = None,
              replicas: dict[str, str] | None = None,
              router: bool = False) -> dict:
    """Run one open-loop stage at `rate` req/s: the dispatcher sleeps
    to each pre-drawn arrival time and fires a daemon thread per
    request — completions never gate arrivals. A bounded in-flight cap
    (way above anything a healthy stage reaches) keeps a wedged server
    from accumulating threads without limit; capped sends are recorded
    as harness errors, never silently dropped. `carryover` is the
    cross-stage straggler registry: threads still blocked from EARLIER
    stages count against the cap too (pass the same list to every
    stage of a sweep), otherwise a wedged server accumulates up to
    max_inflight threads PER STAGE."""
    duration = cfg["duration"]
    arrivals = poisson_arrivals(rng, rate, duration)
    bodies = [build_body(rng, cfg) for _ in arrivals]
    results: list[dict] = []
    lock = threading.Lock()
    threads: list[threading.Thread] = []
    carry = carryover if carryover is not None else []
    carry[:] = [t for t in carry if t.is_alive()]

    def worker(body: dict) -> None:
        rec = send_stream(base, body, cfg["request_timeout"])
        with lock:
            results.append(rec)

    m0 = scrape_metrics(base)
    r0 = {
        rid: scrape_metrics(u) for rid, u in (replicas or {}).items()
    }
    t0 = time.monotonic()
    for off, body in zip(arrivals, bodies):
        delay = t0 + off - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        live = sum(t.is_alive() for t in threads) + sum(
            t.is_alive() for t in carry
        )
        if live >= cfg["max_inflight"]:
            with lock:
                results.append({
                    "status": None, "ok": False, "ttft_s": None,
                    "per_token_s": None, "e2e_s": None, "tokens": 0,
                    "cost": None, "error": "harness_inflight_cap",
                    "router_retries": 0, "replica": None,
                })
            continue
        t = threading.Thread(target=worker, args=(body,), daemon=True)
        t.start()
        threads.append(t)
    deadline = time.monotonic() + cfg["drain_s"]
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
    hung = sum(t.is_alive() for t in threads)
    carry.extend(t for t in threads if t.is_alive())
    m1 = scrape_metrics(base)
    r1 = {
        rid: scrape_metrics(u) for rid, u in (replicas or {}).items()
    }
    with lock:
        # Snapshot: hung daemon workers may still append after the
        # drain; aggregation must see one consistent list.
        snapshot = list(results)
    st = aggregate_stage(
        rate, duration, snapshot, hung, m0, m1,
        cfg["slo_ttft"], cfg["slo_per_token"],
        replica_scrapes=(r0, r1) if replicas else None,
        router=router,
    )
    # Engine step-timeline snapshot at stage end: what the engine(s)
    # were actually doing as this offered load drained — per replica
    # behind a router (the router has no engine loop of its own). The
    # memory block rides the same per-target split (pool + page
    # lifetimes + device-time split live on the engines).
    if replicas:
        st["timeline"] = {
            rid: fetch_timeline(u) for rid, u in replicas.items()
        }
        st["memory"] = {
            rid: memory_block(
                r0.get(rid, ""), r1[rid], fetch_pages_summary(u),
                st["timeline"].get(rid) or {},
            )
            for rid, u in replicas.items()
        }
    else:
        st["timeline"] = fetch_timeline(base, n=256)
        st["memory"] = memory_block(
            m0, m1, fetch_pages_summary(base), st["timeline"]
        )
    return st


# ---------------------------------------------------------------------------
# Knee + report schema + gate
# ---------------------------------------------------------------------------


def find_knee(stages: list[dict], good_frac: float = 0.9) -> dict | None:
    """The saturation knee: the highest offered load whose stage still
    met the SLO for >= good_frac of its requests, with every
    lower-load stage healthy too (prefix property — a sick low-load
    stage caps the knee below it). None = saturated at the lowest
    offered load."""
    knee = None
    for i, st in enumerate(stages):
        if st["sent"] > 0 and st["slo_good_frac"] >= good_frac:
            knee = i
        else:
            break
    if knee is None:
        return None
    st = stages[knee]
    return {
        "index": knee,
        "offered_rps": st["offered_rps"],
        "goodput_tps": st["goodput_tps"],
        "saturated": knee < len(stages) - 1,
    }


_STAGE_KEYS = (
    "offered_rps", "sent", "ok", "good", "slo_good_frac", "goodput_tps",
    "completed_tps", "ttft_s", "per_token_s", "server_ttft_s", "errors",
    "anomalies", "speculation", "audit", "cost", "timeline", "memory",
)


def _stage_memory_blocks(st: dict) -> list[dict]:
    """The stage's memory blocks — one for a single target, one per
    replica behind a router (error entries excluded)."""
    mem = st.get("memory")
    if not isinstance(mem, dict):
        return []
    if "pool" in mem:
        return [mem]
    return [
        b for b in mem.values() if isinstance(b, dict) and "pool" in b
    ]


def validate_report(report: dict) -> list[str]:
    """Schema well-formedness: the shape downstream tooling (CI gates,
    dashboards diffing BENCH_loadgen.json across PRs) depends on.
    Returns problems, [] when clean."""
    probs = []
    for k in ("bench", "config", "stages", "knee", "gate"):
        if k not in report:
            probs.append(f"missing top-level key {k!r}")
    if report.get("bench") != "loadgen":
        probs.append("bench != 'loadgen'")
    stages = report.get("stages") or []
    if not stages:
        probs.append("no stages")
    for i, st in enumerate(stages):
        for k in _STAGE_KEYS:
            if k not in st:
                probs.append(f"stage {i} missing {k!r}")
        for k in ("p50", "p95", "p99"):
            if k not in (st.get("ttft_s") or {}):
                probs.append(f"stage {i} ttft_s missing {k!r}")
            if k not in (st.get("per_token_s") or {}):
                probs.append(f"stage {i} per_token_s missing {k!r}")
        for k in ANOMALY_KINDS:
            if k not in (st.get("anomalies") or {}):
                probs.append(f"stage {i} anomalies missing {k!r}")
        for k in ("429", "503", "504", "transport"):
            if k not in (st.get("errors") or {}):
                probs.append(f"stage {i} errors missing {k!r}")
    knee = report.get("knee")
    if knee is not None and not isinstance(knee, dict):
        probs.append("knee is neither null nor an object")
    if isinstance(knee, dict):
        for k in ("index", "offered_rps", "goodput_tps", "saturated"):
            if k not in knee:
                probs.append(f"knee missing {k!r}")
    return probs


def check_cost_ledger(base: str) -> list[str]:
    """Every finished request in the flight recorder must carry a
    COMPLETE cost ledger (the acceptance bar for the per-request
    attribution path). The key list is the scheduler's own contract
    (utils/metrics.REQUEST_COST_KEYS) — one source of truth."""
    from oryx_tpu.utils.metrics import REQUEST_COST_KEYS

    with urllib.request.urlopen(
        base + "/debug/requests?state=done", timeout=30
    ) as r:
        body = json.load(r)
    if body.get("engine") not in ("continuous", "router"):
        # The window batcher has no cost ledger (or SLO detectors):
        # one clear reason beats N "missing every key" lines. The
        # router's merged recorder carries its replicas' ledgers.
        return [
            "cost-ledger audit requires a scheduler engine or a "
            f"router (server reports engine={body.get('engine')!r})"
        ]
    reqs = body.get("requests", [])
    if not reqs:
        return ["no finished requests in /debug/requests?state=done"]
    probs = []
    for rec in reqs:
        cost = (rec.get("meta") or {}).get("cost")
        missing = [
            k for k in REQUEST_COST_KEYS
            if not isinstance(cost, dict) or k not in cost
        ]
        if missing:
            probs.append(
                f"request {rec.get('id')}: cost ledger missing {missing}"
            )
    return probs


def evaluate_gate(report: dict, *, ledger_problems: list[str],
                  require_affinity: float | None = None,
                  vs_single: bool = False,
                  check_memory: bool = False) -> dict:
    """Pass/fail: schema valid, a knee exists, and ZERO SLO-detector
    firings (and zero hung/transport casualties) at or below it.
    Router sweeps add: the sweep-wide affinity hit rate must exceed
    `require_affinity` (the shared-prefix mix must actually land hot),
    and with `vs_single` the knee must sit at STRICTLY higher offered
    load than the recorded single-replica baseline's. `check_memory`
    (self-booted targets) adds the memory-observatory bars: zero
    leaked pages after the sweep drains (the end-of-stage snapshot's
    free + cache must cover the pool with no slot/shared residue),
    nonzero page-lifetime samples across the sweep, and — when the
    device-time sampler is armed — a per-kind split that stays within
    its sampled wall windows."""
    reasons = list(validate_report(report))
    reasons += ledger_problems
    if check_memory:
        for rid, a in (report.get("memory_audit") or {}).items():
            if a.get("leaked"):
                reasons.append(
                    f"leaked pages on {rid} after drain: "
                    f"slot={a.get('slot')} shared={a.get('shared')} "
                    f"free={a.get('free')} cache={a.get('cache')} of "
                    f"{a.get('num_pages')} (want slot=shared=0, "
                    "free+cache==pool)"
                )
        blocks = [
            b for st in report.get("stages", [])
            for b in _stage_memory_blocks(st)
        ]
        if not blocks:
            reasons.append(
                "no memory block on any stage (the /debug/pages "
                "observatory never answered)"
            )
        lifetime = sum(
            (b.get("page_lifetime_s") or {}).get("count") or 0
            for b in blocks
        )
        if blocks and lifetime <= 0:
            reasons.append(
                "zero page-lifetime samples across the sweep (the "
                "allocator's free-time observatory hook never fired)"
            )
        for st in report.get("stages", []):
            for b in _stage_memory_blocks(st):
                dev = b.get("device_time_s") or {}
                wall = b.get("sampled_wall_s") or {}
                for k, v in dev.items():
                    w = wall.get(k)
                    if w is not None and v > w * 1.1 + 0.05:
                        reasons.append(
                            f"device-time split kind {k!r} "
                            f"({v:.3f}s) exceeds its sampled wall "
                            f"window ({w:.3f}s) at offered "
                            f"{st['offered_rps']:g} rps"
                        )
        if (report.get("config") or {}).get("profile_sample_every"):
            if not any(b.get("sampled_wall_s") for b in blocks):
                reasons.append(
                    "device-time sampler armed but no sampled wall "
                    "windows recorded across the sweep"
                )
    knee = report.get("knee")
    if require_affinity is not None:
        hits = sum(
            (st.get("router") or {}).get("affinity", {}).get("hits") or 0
            for st in report.get("stages", [])
        )
        misses = sum(
            (st.get("router") or {}).get("affinity", {}).get("misses") or 0
            for st in report.get("stages", [])
        )
        rate = hits / (hits + misses) if hits + misses > 0 else 0.0
        report["affinity_hit_rate"] = round(rate, 4)
        if rate <= require_affinity:
            reasons.append(
                f"affinity hit rate {rate:.3f} <= {require_affinity} "
                "on the shared-prefix mix (routing is not preserving "
                "cache locality)"
            )
    if vs_single:
        single = (report.get("single_baseline") or {}).get("knee")
        if single is None:
            reasons.append(
                "--gate-vs-single: no single-replica baseline knee "
                "available to compare against"
            )
        elif knee is None or knee["offered_rps"] <= single["offered_rps"]:
            got = None if knee is None else knee["offered_rps"]
            reasons.append(
                f"router knee at offered {got} rps is not strictly "
                f"above the single-replica knee at "
                f"{single['offered_rps']} rps"
            )
    if knee is None:
        reasons.append(
            "saturated at the lowest offered load (no knee found)"
        )
    else:
        for st in report["stages"][: knee["index"] + 1]:
            fired = sum(st["anomalies"].values())
            if fired:
                reasons.append(
                    f"{fired:g} SLO-detector firing(s) at offered "
                    f"{st['offered_rps']:g} rps (at/below the knee)"
                )
            capped = st["errors"].get("harness_inflight_cap", 0)
            if st["hung"] or st["errors"]["transport"] or capped:
                reasons.append(
                    f"{st['hung']} hung / "
                    f"{st['errors']['transport']} transport-failed / "
                    f"{capped} harness-capped request(s) at offered "
                    f"{st['offered_rps']:g} rps (at/below the knee)"
                )
    return {"passed": not reasons, "reasons": reasons}


# ---------------------------------------------------------------------------
# Self-boot tiny server (smoke / no --base-url)
# ---------------------------------------------------------------------------


class _CharTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


def boot_tiny_server(args, *, replica_id: str | None = None,
                     params=None, cfg=None,
                     profile_sample_every: int | None = None,
                     journal_path: str | None = None):
    """In-process tiny-geometry continuous-engine server with the SLO
    detectors ARMED (they are the gate). Returns (srv, base_url).
    profile_sample_every overrides the CLI value (the fleet boot
    disables sampling per replica — jax's profiler is process-global
    and N in-process engines would contend for it)."""
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve import api_server
    from oryx_tpu.serve.pipeline import OryxInference

    if cfg is None:
        cfg = cfg_lib.oryx_tiny()
    if params is None:
        params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_CharTokenizer(), params, cfg)
    speculate = getattr(args, "speculate", 0)
    if profile_sample_every is None:
        profile_sample_every = getattr(args, "profile_sample_every", 0)
    srv = api_server.build_server(
        pipe, port=0, engine="continuous", num_slots=2, page_size=16,
        decode_chunk=4, max_ctx=512, prefill_chunk=32,
        ragged=bool(speculate), speculate=speculate,
        kv_dtype=getattr(args, "kv_dtype", "bf16"),
        host_cache_bytes=getattr(args, "host_cache_bytes", 0),
        profile_sample_every=profile_sample_every,
        ttft_slo=args.server_ttft_slo,
        queue_depth_slo=args.server_queue_depth_slo,
        replica_id=replica_id,
        journal_path=journal_path,
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def boot_tiny_fleet(args, n: int):
    """N tiny replicas (shared tiny params — one compile, n engines)
    behind a prefix-affinity router. Returns (replica_srvs, router_srv,
    router_base, {rid: replica_base})."""
    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve.router import build_router

    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    servers, bases = [], {}
    for i in range(n):
        srv, base = boot_tiny_server(
            args, replica_id=f"r{i}", params=params, cfg=cfg,
            profile_sample_every=0,
        )
        servers.append(srv)
        bases[f"r{i}"] = base
    rsrv = build_router(
        sorted(bases.items()), port=0, poll_s=0.2,
    )
    threading.Thread(target=rsrv.serve_forever, daemon=True).start()
    return (
        servers, rsrv,
        f"http://127.0.0.1:{rsrv.server_address[1]}", bases,
    )


def warmup(base: str, cfg: dict, rng: random.Random) -> None:
    """Compile the prefill buckets the sweep will hit BEFORE measuring
    — first-touch XLA compiles belong to deployment, not to the
    latency distribution a capacity claim rests on."""
    seen = set()
    for shared in (False, True):
        for chars in cfg["prompt_chars_choices"]:
            key = (shared, chars)
            if key in seen:
                continue
            seen.add(key)
            body = {
                "messages": (
                    [{"role": "system",
                      "content": cfg["shared_prefixes"][0]}]
                    if shared and cfg["shared_prefixes"] else []
                ) + [{
                    "role": "user",
                    "content": "warmup: " + filler_text(rng, chars),
                }],
                "max_tokens": max(cfg["max_tokens_choices"]),
                "stream": True,
                "stream_options": {"include_usage": True},
            }
            send_stream(base, body, cfg["request_timeout"])


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def run(argv=None) -> dict:
    ap = argparse.ArgumentParser(
        description="open-loop load/SLO capacity harness "
        "(see module docstring)"
    )
    ap.add_argument("--base-url", default=None,
                    help="target server; omitted = boot a tiny CPU "
                    "server in-process")
    ap.add_argument("--rates", default="1,2,4,8",
                    help="comma-separated offered loads (req/s), "
                    "swept in order")
    ap.add_argument("--duration", type=float, default=15.0,
                    help="arrival window per stage (s)")
    ap.add_argument("--drain-s", type=float, default=60.0,
                    help="max wait for stragglers after each stage")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-tokens-choices", default="8,16,32")
    ap.add_argument("--prompt-chars-choices", default="48,128")
    ap.add_argument("--shared-prefix-frac", type=float, default=0.5,
                    help="fraction of requests carrying a shared "
                    "system prompt (exercises the prefix cache)")
    ap.add_argument("--shared-prefix-count", type=int, default=2)
    ap.add_argument("--shared-prefix-chars", type=int, default=200)
    ap.add_argument("--slo-ttft", type=float, default=30.0,
                    help="client goodput SLO: TTFT bound (s)")
    ap.add_argument("--slo-per-token", type=float, default=None,
                    help="client goodput SLO: per-token latency bound")
    ap.add_argument("--server-ttft-slo", type=float, default=30.0,
                    help="self-boot server's --ttft-slo (detector arm)")
    ap.add_argument("--server-queue-depth-slo", type=int, default=16,
                    help="self-boot server's --queue-depth-slo")
    ap.add_argument("--knee-good-frac", type=float, default=0.9,
                    help="a stage below the knee must meet the SLO for "
                    "at least this request fraction")
    ap.add_argument("--speculate", type=int, default=0, metavar="K",
                    help="self-booted server only: serve with the "
                    "speculative ragged engine (--ragged --speculate K "
                    "semantics); the per-stage speculation block then "
                    "reports accepted-tokens/step and draft economics")
    ap.add_argument("--kv-dtype", choices=["bf16", "int8"],
                    default="bf16",
                    help="self-booted server: paged KV pool storage "
                    "format (int8 = quantized pages with per-page "
                    "scales — ~2x resident KV tokens per page budget). "
                    "Stamped into the report's provenance; "
                    "bench_compare REFUSES cross-dtype diffs.")
    ap.add_argument("--host-cache-bytes", type=int, default=0,
                    help="self-booted server: host-RAM prefix-cache "
                    "spill tier budget in bytes (0 = off); the "
                    "per-stage memory block then carries host-tier "
                    "rows (spilled pages, reload hit economics)")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="single self-booted server only: arm the "
                    "engine decision journal at PATH (serve/journal.py) "
                    "— the sweep's decision stream lands as a "
                    "replayable artifact (scripts/replay_journal.py) "
                    "and the journal provenance (armed, path, entry "
                    "count) is stamped into the report's config block")
    ap.add_argument("--profile-sample-every", type=int, default=0,
                    metavar="N",
                    help="self-booted server only: arm the sampled "
                    "device-time attributor (every N engine steps one "
                    "dispatch is profiled; feeds the per-stage memory "
                    "block's device-time split). Router fleets keep it "
                    "off per replica — jax's profiler is "
                    "process-global")
    ap.add_argument("--request-timeout", type=float, default=300.0)
    ap.add_argument("--max-inflight", type=int, default=256)
    ap.add_argument("--out", default="BENCH_loadgen.json",
                    help="report path ('' disables). The default "
                    "deliberately refreshes the tracked artifact: "
                    "every PR's gate re-runs the same seeded sweep "
                    "and commits the new capacity point, which is the "
                    "regression-diff workflow (docs/OBSERVABILITY.md)")
    ap.add_argument("--gate", action="store_true",
                    help="exit nonzero when the gate fails (implied "
                    "by --smoke)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: tiny self-boot server, short sweep, "
                    "hard gate + schema + cost-ledger audit")
    ap.add_argument("--router", type=int, default=0, metavar="N",
                    help="multi-replica mode: boot N tiny replicas "
                    "behind a prefix-affinity router (serve/router.py) "
                    "and sweep THROUGH the router; the report gains "
                    "per-stage per-replica goodput splits, affinity "
                    "hit rate, and router-level retry/503 "
                    "classification (self-boot only)")
    ap.add_argument("--gate-vs-single", action="store_true",
                    help="router mode: fail the gate unless the "
                    "router sweep's knee sits at STRICTLY higher "
                    "offered load than the single-replica knee "
                    "recorded in the pre-existing --out report "
                    "(meaningful on multi-core hosts; N replicas on "
                    "one core share it)")
    args = ap.parse_args(argv)
    if args.router and args.base_url:
        ap.error("--router self-boots a fleet; drop --base-url")
    if args.gate_vs_single and not args.router:
        ap.error("--gate-vs-single only applies to --router sweeps")
    if args.journal and (args.router or args.base_url):
        # One journal file per scheduler: a fleet would collide on the
        # path, and a remote target's journal lives on its own disk.
        ap.error("--journal applies to the single self-booted server")
    if args.smoke:
        args.base_url = None
        args.rates = "1,4"
        args.duration = 5.0
        args.drain_s = 60.0
        args.max_tokens_choices = "4,6"
        args.prompt_chars_choices = "32,64"
        args.gate = True
        if not args.router:
            # The smoke's committed artifact must carry a real
            # device-time split (the memory block's acceptance bar);
            # every 5th engine step is cheap on the tiny geometry.
            args.profile_sample_every = args.profile_sample_every or 5
        if args.router:
            # The router smoke is the AFFINITY gate: emphasize the
            # shared-prefix mix so the >0.5 hit-rate bar measures
            # routing quality, not the unique-prompt fraction (a
            # fully-unique request can never affinity-hit).
            args.shared_prefix_frac = 0.75

    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    rng = random.Random(args.seed)
    shared_rng = random.Random(args.seed + 1)
    cfg = {
        "duration": args.duration,
        "drain_s": args.drain_s,
        "request_timeout": args.request_timeout,
        "max_inflight": args.max_inflight,
        "slo_ttft": args.slo_ttft,
        "slo_per_token": args.slo_per_token,
        "max_tokens_choices": [
            int(x) for x in args.max_tokens_choices.split(",")
        ],
        "prompt_chars_choices": [
            int(x) for x in args.prompt_chars_choices.split(",")
        ],
        "shared_prefix_frac": args.shared_prefix_frac,
        "shared_prefixes": [
            filler_text(shared_rng, args.shared_prefix_chars)
            for _ in range(args.shared_prefix_count)
        ],
    }

    srv = None
    fleet: list = []
    rsrv = None
    replica_bases: dict[str, str] | None = None
    base = args.base_url
    self_booted = base is None
    # Router mode compares against the PRIOR single-replica report at
    # --out (the same seeded sweep the single smoke just wrote): its
    # knee becomes the baseline the multi-replica knee must beat.
    single_baseline = None
    if args.router and args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prior = json.load(f)
            if not (prior.get("config") or {}).get("router_replicas"):
                single_baseline = {
                    "knee": prior.get("knee"),
                    "rates_rps": (prior.get("config") or {}).get(
                        "rates_rps"
                    ),
                }
        except (OSError, ValueError):
            single_baseline = None
    try:
        if args.router:
            fleet, rsrv, base, replica_bases = boot_tiny_fleet(
                args, args.router
            )
        elif self_booted:
            srv, base = boot_tiny_server(args, journal_path=args.journal)
        warmup(base, cfg, random.Random(args.seed + 2))
        if replica_bases:
            # The affinity router concentrates the warmup on one
            # replica; touch every OTHER engine once directly so no
            # replica meets its first request mid-measurement. (The
            # XLA programs are already compiled — tiny replicas share
            # one process-wide jit cache — this warms each engine
            # thread's first-admission path.)
            for rb in replica_bases.values():
                send_stream(rb, {
                    "messages": [{"role": "user", "content": "warm"}],
                    "max_tokens": 2, "stream": True,
                }, cfg["request_timeout"])
        stages = []
        stragglers: list = []  # live threads from earlier stages
        for rate in rates:
            print(f"stage: offered {rate:g} req/s for "
                  f"{args.duration:g}s ...", file=sys.stderr)
            st = run_stage(
                base, rate, cfg, rng, carryover=stragglers,
                replicas=replica_bases, router=bool(args.router),
            )
            print(
                f"  sent={st['sent']} ok={st['ok']} "
                f"good_frac={st['slo_good_frac']} "
                f"goodput={st['goodput_tps']} tok/s "
                f"ttft_p99={st['ttft_s']['p99']}", file=sys.stderr,
            )
            stages.append(st)
        knee = find_knee(stages, args.knee_good_frac)
        # Provenance stamps (scripts/bench_compare.py refuses
        # comparisons across any of these): the git revision this run
        # measured, the backend class (a cpu self-boot is a labeled
        # cpu_proxy run, the same convention as bench.py — never
        # comparable against a TPU baseline), the target's own
        # build_info identity, and the engine flags in effect.
        import jax

        from oryx_tpu.serve.api_server import _git_revision

        scrape = scrape_metrics(base)
        server_build = (
            build_info_labels(scrape, "oryx_serving_build_info")
            or build_info_labels(scrape, "oryx_router_build_info")
        )
        # Pool-geometry provenance: the memory blocks are only
        # comparable across runs serving from the SAME pool shape —
        # scripts/bench_compare.py refuses a drifted geometry instead
        # of diffing page counts across different pools.
        pool_probe = fetch_pages_summary(
            next(iter(replica_bases.values())) if replica_bases
            else base
        )
        pool_geom = {
            "num_pages": pool_probe.get("num_pages"),
            "page_size": pool_probe.get("page_size"),
            # Device bytes of the whole KV pool (codes + scales on a
            # quantized pool): pages are token-granular and
            # dtype-blind, so THIS is the unit --kv-dtype int8
            # halves at identical geometry-in-tokens.
            "kv_pool_bytes": pool_probe.get("kv_pool_bytes"),
        }
        # End-of-sweep zero-leak audit (self-booted targets only —
        # a remote server's quiescence is unknowable from here): with
        # every stage drained, no slot may still hold pages and the
        # free list plus the prefix cache's references must cover the
        # whole pool.
        # Decision-journal provenance: when --journal armed the flight
        # recorder, the sweep's decision stream is itself an artifact
        # (scripts/replay_journal.py replays it offline) — record
        # where it landed and how many decisions it carries so the
        # capacity number stays re-derivable. Unarmed/remote/router
        # runs stamp armed=false / null honestly.
        journal_prov = None
        if not args.base_url and not args.router:
            try:
                with urllib.request.urlopen(
                    base + "/debug/journal?n=0", timeout=30
                ) as r:
                    jbody = json.load(r)
                journal_prov = {
                    "armed": bool(jbody.get("armed")),
                    "path": jbody.get("path"),
                    "entries": jbody.get("total"),
                }
            except Exception as e:
                journal_prov = {"error": f"{type(e).__name__}: {e}"}
        memory_audit = None
        if not args.base_url:
            memory_audit = {}
            targets = replica_bases or {"self": base}
            for rid, b in sorted(targets.items()):
                s = fetch_pages_summary(b).get("summary") or {}
                memory_audit[rid] = {
                    **{k: s.get(k) for k in (
                        "num_pages", "free", "slot", "cache", "shared",
                        "reconciled",
                    )},
                    "leaked": not (
                        s.get("reconciled")
                        and s.get("slot") == 0
                        and s.get("shared") == 0
                        and (s.get("free", 0) + s.get("cache", 0)
                             == s.get("num_pages"))
                    ),
                }
        if args.base_url:
            backend = "remote"
            # A remote target's engine flags are unknowable from the
            # client side — stamping the harness's own (unused) flags
            # would let bench_compare diff across a server config
            # change instead of refusing. Null = honestly unknown;
            # server_build carries what the target self-declares.
            speculate = ragged = None
        else:
            backend = jax.default_backend()
            if backend != "tpu":
                backend = "cpu_proxy"
            speculate = args.speculate or 0
            ragged = bool(args.speculate)
        report = {
            "bench": "loadgen",
            "config": {
                "gated": bool(args.gate),
                "git_rev": _git_revision(),
                "backend": backend,
                "server_build": server_build,
                "engine": {
                    "engine": server_build.get("engine"),
                    "ragged": ragged,
                    "speculate": speculate,
                    "router_replicas": args.router or None,
                },
                "base_url": args.base_url or (
                    f"self-boot router x{args.router} (cpu)"
                    if args.router else "self-boot tiny (cpu)"
                ),
                "rates_rps": rates,
                "duration_s": args.duration,
                "seed": args.seed,
                "slo_ttft_s": args.slo_ttft,
                "slo_per_token_s": args.slo_per_token,
                "knee_good_frac": args.knee_good_frac,
                "max_tokens_choices": cfg["max_tokens_choices"],
                "prompt_chars_choices": cfg["prompt_chars_choices"],
                "shared_prefix_frac": args.shared_prefix_frac,
                "shared_prefix_chars": args.shared_prefix_chars,
                "smoke": args.smoke,
                "router_replicas": args.router or None,
                "pool": pool_geom,
                # KV-pool wire format + host-tier geometry provenance:
                # page counts from pools storing different bytes per
                # token are category errors (a remote target's format
                # is unknowable from here -> null, like the engine
                # flags above).
                "kv_dtype": (
                    None if args.base_url else args.kv_dtype
                ),
                "host_cache_bytes": (
                    None if args.base_url else args.host_cache_bytes
                ),
                # The EFFECTIVE cadence: router fleets boot every
                # replica with sampling off (jax's profiler is
                # process-global), so stamping the CLI value would
                # false-fail the armed-but-no-windows gate bar and
                # mis-key bench_compare's provenance refusal.
                "profile_sample_every": (
                    None if args.base_url
                    else 0 if args.router
                    else args.profile_sample_every
                ),
                # Flight-recorder provenance (NOT a comparability key:
                # journaling observes, never perturbs — CI-gated).
                "journal": journal_prov,
            },
            "stages": stages,
            "knee": knee,
            "gate": {},
            "memory_audit": memory_audit,
        }
        if args.router and single_baseline is not None:
            report["single_baseline"] = single_baseline
        # Cost-ledger audit rides the same server session (the flight
        # recorder still holds the sweep's requests; the router merges
        # its replicas').
        ledger_problems = check_cost_ledger(base)
        report["gate"] = evaluate_gate(
            report, ledger_problems=ledger_problems,
            require_affinity=0.5
            if args.router and args.shared_prefix_frac >= 0.5 else None,
            vs_single=args.gate_vs_single,
            check_memory=not args.base_url,
        )
    finally:
        if rsrv is not None:
            rsrv.stop_prober()
        for s in fleet:
            if s.scheduler is not None:
                s.scheduler.close()
            s.shutdown()
        if rsrv is not None:
            rsrv.shutdown()
        if srv is not None:
            if srv.scheduler is not None:
                srv.scheduler.close()
            srv.shutdown()
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    return report


def main(argv=None) -> None:
    report = run(argv)
    print(json.dumps(report, indent=2))
    gate = report["gate"]
    if report["config"]["gated"] and not gate["passed"]:
        for r in gate["reasons"]:
            print(f"FAIL: {r}", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
