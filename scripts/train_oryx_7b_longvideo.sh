#!/usr/bin/env bash
# Long-video SFT: 256-frame records, ring attention over sp=4
# (sequence/context parallelism; ops/ring_attention.py). The reference has
# no SP — it relies on 16x compression alone (SURVEY.md §5 "Long-context");
# this config adds the TPU-idiomatic headroom path for low-compression runs.
set -euo pipefail
cd "$(dirname "$0")/.."

DATA=${DATA:?path to conversation-records json}
TOKENIZER=${TOKENIZER:?path to Qwen2 tokenizer dir}

python -m oryx_tpu.train.cli \
  --config scripts/configs/oryx_7b_longvideo.json \
  --data "$DATA" \
  --tokenizer-path "$TOKENIZER" \
  --video-frames 256 \
  --sharding fsdp \
  --metrics-path logs/oryx7b_video_metrics.jsonl \
  --output-dir models/oryx7b-longvideo \
  "$@"
