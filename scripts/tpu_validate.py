"""On-chip Pallas kernel validation: parity vs the XLA attention path
plus a forward/backward timing probe. Reproduces the evidence recorded
in TPU_VALIDATION.md with one command:

    python scripts/tpu_validate.py            # real chip (or CPU interpret)
    python scripts/tpu_validate.py --fast     # parity only, no timings

Case shapes match the TPU_VALIDATION.md tables at the default --seq
(1024 on TPU): causal GQA B2 T<seq> Hq8 Hk2 D128, segment-packed
B1 T<3*seq/4> H4 D64, KV-cache decode B4 Tq8 S<2*seq>; the timing probe
runs B4 T<timing-seq=4096> Hq8 Hk2 D128 with on-device reduction sync.
Case 4 certifies the END-TO-END decode (prefill kernel + cached decode
under the early-exit while_loop, and the split-prefill prefix-cache
path) by greedy token streams: those lines carry `prefix_agreement`
(mean first-divergence fraction; 1.0 = bitwise) instead of
`max_abs_diff`. Every line has `"pass"`; the script EXITS NONZERO if
any case fails, so a CI smoke run (CPU interpret mode; small --seq
shrinks every case) actually fails on regressions.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _qkv(jax, jnp, key, B, Tq, Tk, Hq, Hk, D, dtype):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (B, Tq, Hq, D), dtype),
        jax.random.normal(kk, (B, Tk, Hk, D), dtype),
        jax.random.normal(kv, (B, Tk, Hk, D), dtype),
    )


def parity_cases(args) -> bool:
    import jax
    import jax.numpy as jnp

    from oryx_tpu.ops.attention import attention as xla_attention
    from oryx_tpu.ops.pallas.flash_attention import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    # Outputs are O(1): bf16 forward diffs land ~2 ulp (TPU_VALIDATION.md
    # measured 1.6e-2); gradients are O(10s) so the backward bound is
    # absolute-loose / relatively tight.
    fwd_tol = 3e-2 if on_tpu else 1e-3
    bwd_tol = 5e-1 if on_tpu else 1e-3
    T = args.seq
    ok = True

    def record(name, got, ref, tol):
        nonlocal ok
        diff = float(
            jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32)))
        )
        passed = diff <= tol
        ok = ok and passed
        print(json.dumps({
            "case": name, "max_abs_diff": round(diff, 6), "tol": tol,
            "pass": passed,
        }))

    # 1. Causal GQA prefill forward + backward (B2 T<seq> Hq8 Hk2 D128).
    q, k, v = _qkv(jax, jnp, jax.random.key(0), 2, T, T, 8, 2, 128, dtype)
    record(
        "causal_gqa_fwd",
        flash_attention(q, k, v, causal=True),
        xla_attention(q, k, v, causal=True),
        fwd_tol,
    )

    def loss(attn):
        return lambda q, k, v: jnp.sum(
            attn(q, k, v, causal=True).astype(jnp.float32) ** 2
        )

    gp = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(xla_attention), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gp, gx):
        record(f"causal_gqa_bwd_{name}", a, b, bwd_tol)

    # 2. Segment-packed (ViT varlen) forward: B1 T<3*seq/4> H4 D64,
    #    uneven segments.
    P = max(3 * T // 4, 16)
    seg = np.zeros(P, np.int32)
    bounds = [0, P // 5, P // 2, (3 * P) // 4, P]
    for s, (lo, hi) in enumerate(zip(bounds[:-1], bounds[1:]), start=1):
        seg[lo:hi] = s
    seg = jnp.asarray(seg)[None]
    q2, k2, v2 = _qkv(jax, jnp, jax.random.key(1), 1, P, P, 4, 4, 64, dtype)
    record(
        "segment_packed_fwd",
        flash_attention(
            q2, k2, v2, causal=False, q_segment_ids=seg, kv_segment_ids=seg
        ),
        xla_attention(q2, k2, v2, causal=False,
                      q_segment_ids=seg, kv_segment_ids=seg),
        fwd_tol,
    )

    # 3. KV-cache decode layout: B4 Tq8 S<2*seq>, arbitrary q positions
    #    (the regression case for the causal DMA-clamp fix).
    S = 2 * T
    base = jnp.asarray([T - 9, T + 3, 5 + 7, S - 9], jnp.int32)
    qpos = base[:, None] + jnp.arange(8, dtype=jnp.int32)[None, :]
    q3, k3, v3 = _qkv(jax, jnp, jax.random.key(2), 4, 8, S, 8, 2, 128, dtype)
    kv_mask = (
        jnp.arange(S)[None, :] <= qpos[:, -1:]
    ).astype(jnp.int32)
    kw = dict(causal=True, q_positions=qpos, kv_positions=None,
              kv_mask=kv_mask)
    record(
        "kv_cache_decode_fwd",
        flash_attention(q3, k3, v3, **kw),
        xla_attention(q3, k3, v3, **kw),
        fwd_tol,
    )

    # 4. END-TO-END decode certification (round-4 surface): greedy
    #    generate() — prefill kernel + cached decode under the early-exit
    #    while_loop — Pallas vs XLA token agreement, plus split-prefill
    #    (the ChatSession prefix-cache path: prefill a prefix into the
    #    cache, continue with a suffix at start>0) vs one-shot generate,
    #    which must agree with itself per impl.
    from oryx_tpu.config import GenerationConfig, LLMConfig
    from oryx_tpu.models import generate as generate_lib
    from oryx_tpu.models import qwen2

    lcfg = LLMConfig(
        vocab_size=2048, hidden_size=256, intermediate_size=512,
        num_layers=2, num_heads=8, num_kv_heads=2, head_dim=64,
        attention_bias=True,
    )
    gcfg = GenerationConfig(temperature=0.0, eos_token_id=10**9)
    lp = qwen2.init_params(lcfg, jax.random.key(3), dtype=jnp.float32)
    # Scales with --seq so small smoke runs stay small (floor keeps
    # half > the 5-token length stagger below).
    Tp = max(T // 8, 16)
    emb_key = jax.random.key(4)
    embeds = jax.random.normal(emb_key, (2, Tp, 256), dtype) * 0.2
    lengths = jnp.asarray([Tp, Tp - 5], jnp.int32)
    cache_len = 2 * Tp

    def gen(impl, kv_cache=None, start=None, embeds_=None, lengths_=None):
        toks, num, fin = generate_lib.generate(
            lp, lcfg, gcfg,
            inputs_embeds=embeds_ if embeds_ is not None else embeds,
            lengths=lengths_ if lengths_ is not None else lengths,
            max_new_tokens=16, cache_len=cache_len,
            attn_impl=impl, compute_dtype=dtype,
            kv_cache=kv_cache, start=start,
        )
        return np.asarray(toks)

    def record_agreement(name, a, b, min_frac):
        """Greedy decode is autoregressive: ONE near-tie argmax flip
        diverges every later token, so raw agreement is misleading.
        Score the FIRST-DIVERGENCE point instead: mean over rows of
        (first mismatching step / steps), 1.0 = bitwise identical."""
        nonlocal ok
        steps = a.shape[1]
        fracs = []
        for ra, rb in zip(a, b):
            neq = ra != rb
            fracs.append(
                (int(np.argmax(neq)) if neq.any() else steps) / steps
            )
        frac = float(np.mean(fracs))
        passed = frac >= min_frac
        ok = ok and passed
        print(json.dumps({
            "case": name, "prefix_agreement": round(frac, 4),
            "min": min_frac, "pass": passed,
        }))

    impls = ("pallas", "xla")  # pallas interprets on CPU like cases 1-3
    toks_by_impl = {i: gen(i) for i in impls}
    # bf16 kernel-vs-XLA near-ties can flip a greedy argmax mid-stream;
    # demand the first flip lands in the back half of the window.
    record_agreement(
        "generate_pallas_vs_xla",
        toks_by_impl["pallas"], toks_by_impl["xla"], 0.5,
    )
    for impl in impls:
        # Split prefill: rows share a Tp//2 prefix; continue with the
        # remaining embeds at start=Tp//2. Same math, different
        # schedule — tokens must match the one-shot run per impl.
        half = Tp // 2
        cache = qwen2.init_kv_cache(lcfg, 2, cache_len, dtype=dtype)
        _, _, _, cache = generate_lib.generate(
            lp, lcfg, gcfg, inputs_embeds=embeds[:, :half],
            lengths=jnp.asarray([half, half], jnp.int32),
            max_new_tokens=1, cache_len=cache_len, attn_impl=impl,
            compute_dtype=dtype, kv_cache=cache,
            start=jnp.asarray(0, jnp.int32), return_cache=True,
        )
        split = gen(
            impl, kv_cache=cache, start=jnp.asarray(half, jnp.int32),
            embeds_=embeds[:, half:], lengths_=lengths,
        )
        # Same math, different fp reduction schedule — a near-tie flip
        # is legal even off-TPU, so bitwise identity is not demanded.
        record_agreement(
            f"split_prefill_{impl}", split, toks_by_impl[impl], 0.75,
        )
    return ok


def timing_probe(args):
    import jax
    import jax.numpy as jnp

    from oryx_tpu.ops.attention import attention as xla_attention
    from oryx_tpu.ops.pallas.flash_attention import flash_attention

    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    q, k, v = _qkv(
        jax, jnp, jax.random.key(3), args.batch, args.timing_seq,
        args.timing_seq, 8, 2, 128, dtype,
    )

    def timed(fn, reps):
        # fn reduces ON DEVICE to a scalar, so the sync fetch is 4 bytes —
        # the axon tunnel's per-fetch latency amortizes over `reps`
        # instead of inflating every rep (TPU_VALIDATION.md methodology).
        out = fn(q, k, v)
        float(jax.device_get(out))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(q, k, v)
        float(jax.device_get(out))
        return (time.perf_counter() - t0) / reps * 1e3

    for name, attn in (("flash", flash_attention), ("xla", xla_attention)):
        def fwd(q, k, v, attn=attn):
            return jnp.sum(attn(q, k, v, causal=True).astype(jnp.float32))

        def fwdbwd(q, k, v, attn=attn):
            grads = jax.grad(
                lambda *a: jnp.sum(
                    attn(*a, causal=True).astype(jnp.float32) ** 2
                ),
                argnums=(0, 1, 2),
            )(q, k, v)
            return sum(jnp.sum(g.astype(jnp.float32)) for g in grads)

        print(json.dumps({
            "timing": name,
            "fwd_ms": round(timed(jax.jit(fwd), args.reps), 2),
            "fwdbwd_ms": round(timed(jax.jit(fwdbwd), args.reps), 2),
            "shape": [args.batch, args.timing_seq, 8, 2, 128],
        }))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seq", type=int, default=None,
                    help="parity sequence length (default: 1024 on TPU, "
                    "128 on CPU)")
    ap.add_argument("--timing-seq", type=int, default=4096,
                    help="timing-probe sequence length (the MD table's)")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--fast", action="store_true", help="parity only")
    args = ap.parse_args()

    import jax

    backend = jax.default_backend()
    if args.seq is None:
        args.seq = 1024 if backend == "tpu" else 128
    print(json.dumps({
        "backend": backend,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "seq": args.seq,
    }))
    ok = parity_cases(args)
    if not args.fast and backend == "tpu":
        timing_probe(args)
    if not ok:
        raise SystemExit("kernel parity FAILED (see cases above)")


if __name__ == "__main__":
    main()
