"""AOT proof: real-7B int8 serving fits ONE 16 GB v5e chip.

MIGRATING.md promises "7B-class models fit ONE 16 GB v5e" under
weight-only int8 (`--quantize int8`, utils/quant.py). This compiles the
claim against the actual XLA:TPU compiler (chipless v5e:2x2 topology,
one device) at the TRUE Oryx-7B geometry — no weights materialized:

  * the 64-frame video-QA visual encode (ViT + compressor over the
    packed 4096-patch buffer, the BASELINE config-3 prefill load), and
  * `models/generate.generate` (jitted prefill + decode while-loop)
    over a 1024-token prompt with a 2048-slot KV cache,

both with the int8 param tree (eval_shape of utils/quant.quantize_params
over the fp32 init: int8 kernels + embedding, f32 scales, bf16 cast for
the rest). Per-program totals (args + temps + outputs - aliases) must
sit under the 16 GB HBM; the TPU compiler would refuse at compile time
otherwise (RESOURCE_EXHAUSTED).

    python scripts/estimate_serving_memory.py

One JSON line per program and a summary line. Pinned by
tests/test_aot_serving_7b.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from functools import partial

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GB = 1024**3
_CHILD_ENV = "ORYX_TPU_AOTSRV_CHILD"

# BASELINE config 3 serving shapes: 64-frame video at the per-frame
# patch cap (4096/64 = 64 patches -> 4 visual tokens at 16x), 1024-token
# prompt bucket, 128 new tokens in a 2048-slot cache.
FRAMES = 64
PATCHES = FRAMES * 64
Q_TOKENS = FRAMES * 4
PROMPT_T = 1024
MAX_NEW = 128
CACHE_LEN = 2048


def main() -> None:
    if os.environ.get(_CHILD_ENV) != "1":
        env = dict(os.environ)
        env[_CHILD_ENV] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
        sys.exit(subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env, cwd=REPO,
        ).returncode)

    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import generate as gen_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.utils.quant import quantize_params

    with open(os.path.join(REPO, "scripts/configs/oryx_7b_sft.json")) as f:
        cfg = cfg_lib.OryxConfig.from_dict(json.load(f))

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    dev = topo.devices[0]
    shard = jax.sharding.SingleDeviceSharding(dev)

    from oryx_tpu.utils.quant import quantized_bytes

    params_shape = jax.eval_shape(
        lambda: oryx.init_params(cfg, jax.random.key(0))
    )
    q_shape = jax.eval_shape(
        partial(quantize_params, cast=lambda x: x.astype(jnp.bfloat16)),
        params_shape,
    )
    weight_bytes = quantized_bytes(q_shape)
    llm_bytes = quantized_bytes(q_shape["llm"])
    vis_bytes = weight_bytes - llm_bytes

    def sds(s):
        return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shard)

    q_in = jax.tree.map(sds, q_shape)

    def bsds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=shard)

    def analyze(name, compiled):
        ma = compiled.memory_analysis()
        total = (
            ma.argument_size_in_bytes + ma.temp_size_in_bytes
            + ma.output_size_in_bytes - ma.alias_size_in_bytes
        )
        rec = {
            "program": name,
            "weight_gb": round(weight_bytes / GB, 2),
            "args_gb": round(ma.argument_size_in_bytes / GB, 2),
            "temp_gb": round(ma.temp_size_in_bytes / GB, 2),
            "total_gb": round(total / GB, 2),
            "fits_16gb": bool(total < 16 * GB),
        }
        print(json.dumps(rec), flush=True)
        return rec

    # Program 1: visual encode at the 64-frame packed shapes.
    patch_dim = cfg.vision.patch_size**2 * 3

    def visual(p, patches, seg, pos, reg, qreg):
        return oryx.encode_visual(
            p, cfg, patches, seg, pos, reg, qreg,
            compute_dtype=jnp.bfloat16,
        )

    vis = jax.jit(visual).lower(
        q_in,
        bsds((PATCHES, patch_dim), jnp.float32),
        bsds((PATCHES,), jnp.int32),
        bsds((PATCHES, 2), jnp.float32),
        bsds((PATCHES,), jnp.int32),
        bsds((Q_TOKENS,), jnp.int32),
    ).compile()
    r1 = analyze("visual_encode_64f", vis)

    # Program 2: prefill + decode (the serving generate jit, as the
    # pipeline invokes it: Pallas attention, bf16 compute).
    gen = gen_lib.generate.lower(
        q_in["llm"], cfg.llm, cfg.generation,
        inputs_embeds=bsds((1, PROMPT_T, cfg.llm.hidden_size),
                           jnp.bfloat16),
        lengths=bsds((1,), jnp.int32),
        max_new_tokens=MAX_NEW,
        cache_len=CACHE_LEN,
        key=None,
        attn_impl="pallas",
        compute_dtype=jnp.bfloat16,
    ).compile()
    r2 = analyze("generate_prefill_decode", gen)

    # The SERVING PEAK: the pipeline runs the two programs back to back
    # with the whole int8 tree resident in HBM throughout (per-program
    # args only count the subtree each program reads — XLA DCEs the
    # rest, so neither program's total alone bounds the peak). Peak =
    # resident weights + the larger program's non-weight working set.
    extra_vis = r1["total_gb"] - round(vis_bytes / GB, 2)
    extra_gen = r2["total_gb"] - round(llm_bytes / GB, 2)
    peak = round(weight_bytes / GB + max(extra_vis, extra_gen), 2)
    print(json.dumps({
        "summary": "7b_int8_serving_one_v5e",
        "serving_peak_gb": peak,
        "all_fit": bool(
            r1["fits_16gb"] and r2["fits_16gb"] and peak < 16.0
        ),
    }), flush=True)


if __name__ == "__main__":
    main()
