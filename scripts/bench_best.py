"""Combine the per-sweep winners and run the full bench once with the
union configuration.

The sweeps (scripts/bench_sweep.py) vary one knob at a time; this step
reads their banked per-config results under SWEEP_STATE_DIR, picks the
argmax-by-tok/s config of each sweep, merges their env overrides, and
runs bench.py with the merged env — the evidence for flipping repo
defaults. The sweeps' knobs OVERLAP on BENCH_MOMENT_DTYPE (the remat
combo row and the >8 batch rows both carry bfloat16): the merge is
sorted-by-sweep-name with later sweeps winning, and today every
overlapping key only ever takes the value "bfloat16" — revisit the
resolution if a sweep ever sets a different value for a shared key.
Skips silently-missing sweeps: a partial state dir yields the
best-known combination, not a crash.

    SWEEP_STATE_DIR=/tmp/r4_onchip/sweep_state python scripts/bench_best.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench import _find_json_line  # noqa: E402
from bench_sweep import SWEEPS, _state_path  # noqa: E402


def best_env(state_dir: str) -> dict[str, str]:
    """argmax-by-tok/s config per sweep, CURRENT configs only: banked
    records for configs since edited out of SWEEPS (content-hashed
    filenames that no longer match) must not participate."""
    by_sweep: dict[str, tuple[float, dict]] = {}
    for which, configs in SWEEPS.items():
        for cfg in configs:
            path = _state_path(which, cfg, state_dir)
            if not path or not os.path.exists(path):
                continue
            try:
                rec = json.load(open(path))
            except ValueError:
                continue
            val = rec.get("value")
            if val is None:  # banked deterministic failure (e.g. OOM)
                continue
            if which not in by_sweep or val > by_sweep[which][0]:
                by_sweep[which] = (val, rec.get("config", {}))
    merged: dict[str, str] = {}
    for sweep, (val, cfg) in sorted(by_sweep.items()):
        print(f"# {sweep}: best {val} with {cfg}", flush=True)
        merged.update(cfg)
    return merged


def main() -> None:
    state_dir = os.environ.get("SWEEP_STATE_DIR", "")
    if not state_dir or not os.path.isdir(state_dir):
        print(json.dumps({"error": "no_sweep_state", "dir": state_dir}))
        raise SystemExit(1)
    env = best_env(state_dir)
    if not env:
        print(json.dumps({"error": "no_scored_sweep_results"}))
        raise SystemExit(1)
    print(f"# merged best env: {env}", flush=True)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env={**os.environ, **env}, capture_output=True, text=True,
    )
    sys.stderr.write(proc.stderr or "")
    sys.stdout.write(proc.stdout or "")
    sys.stdout.flush()
    line = _find_json_line(proc.stdout or "")
    err = json.loads(line).get("error") if line else None
    if proc.returncode != 0 and err == "oom":
        # The one-knob-at-a-time winners can exceed HBM in union. That is
        # a final (negative) finding for THIS combination — exit 0 so the
        # watcher does not re-pay a full compile+OOM every cycle; the
        # individual sweep winners remain banked for manual combination.
        print("# merged config OOMs; banking as final", flush=True)
        return
    raise SystemExit(proc.returncode)


if __name__ == "__main__":
    main()
