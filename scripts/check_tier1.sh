#!/usr/bin/env bash
# One-command local tier-1 gate: runs the ROADMAP "Tier-1 verify"
# command VERBATIM (in a subshell, so its trailing `exit $rc` is its
# own exit code), then fails on any regression vs the recorded
# DOTS_PASSED baseline below.
#
# Bump BASELINE_DOTS deliberately when green tests are ADDED; never
# lower it to paper over a regression. Override for experiments with
# ORYX_TIER1_BASELINE=<n>.
set -u
cd "$(dirname "$0")/.."

# 300 = the 274 recorded at PR 1 plus the observability suite added in
# PR 2 (trace/watchdog, debug endpoints, xplane join, conftest guard;
# 305 observed with a warm /tmp/jax_cache), with headroom for the 4
# trainer-family tests that flip with cache state (see CHANGES.md).
BASELINE_DOTS=${ORYX_TIER1_BASELINE:-300}

# --- ROADMAP.md "Tier-1 verify", verbatim -----------------------------------
bash -c "set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=\${PIPESTATUS[0]}; echo DOTS_PASSED=\$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?\$' /tmp/_t1.log | tr -cd . | wc -c); exit \$rc"
rc=$?
# ----------------------------------------------------------------------------

dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo "tier-1: $dots passed (baseline $BASELINE_DOTS, pytest rc=$rc)"
if [ "$dots" -lt "$BASELINE_DOTS" ]; then
    echo "TIER-1 REGRESSION: $dots < baseline $BASELINE_DOTS" >&2
    exit 1
fi
echo "tier-1 OK: no regression vs recorded baseline"

# --- serving observability surface ------------------------------------------
# Boot a short-lived CPU server and verify /metrics (content type,
# oryx_serving_ name prefix, build_info gauge) and the /debug flight
# recorder + trace endpoints are well-formed.
echo "checking serving endpoints (/metrics, /debug/requests, /debug/trace)"
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_serving_endpoints.py; then
    echo "SERVING ENDPOINT CHECK FAILED" >&2
    exit 1
fi
