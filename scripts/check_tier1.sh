#!/usr/bin/env bash
# One-command local tier-1 gate: runs the ROADMAP "Tier-1 verify"
# command VERBATIM (in a subshell, so its trailing `exit $rc` is its
# own exit code), then fails on any regression vs the recorded
# DOTS_PASSED baseline below.
#
# Bump BASELINE_DOTS deliberately when green tests are ADDED; never
# lower it to paper over a regression. Override for experiments with
# ORYX_TIER1_BASELINE=<n>.
set -u
cd "$(dirname "$0")/.."

# 700 = the 680 recorded at PR 18 plus the fused-megastep suite added
# in PR 19 (tests/test_fused_decode.py: fused-vs-K=1 byte parity
# across greedy/sampled/stop-string/eviction/int8/tp=2/speculative
# runs, per-logical-step billing, adaptive-K zero-recompile warmup,
# K-entry journaling with byte-exact fused replay, the journaled
# fuse-plan on auto replay, K=1-replay first-divergence naming, and
# the NeuralDrafter host/device bit-identity + checkpoint contracts;
# ~731 observed), with headroom for load-dependent flakes
# (bench-supervisor probes on one CPU core).
BASELINE_DOTS=${ORYX_TIER1_BASELINE:-700}

# --- oryxlint static analysis (fast, jax-free: fail before pytest) ----------
# Repo-wide by default; ORYX_LINT_CHANGED=1 lints only files changed vs
# HEAD (+ untracked) for the quick local loop (the fast path widens to
# the full tree automatically when the linter or a fixture changed).
#
# Suppression ratchet: 41 = the 22 justified sites recorded at PR 5/6,
# the 3 single-consumer queue-pop `atomicity` suppressions in
# ContinuousScheduler._admit (PR 8), the 6 host-sync lines of
# `_harvest_spec` (PR 11) — the speculative engine's ONE deliberate
# sync point per step, the exact same contract `_harvest_chunk`'s
# region already documents — the identity-re-checked timeout
# clear in `request_profile` (PR 13; the guard is the `is holder`
# re-check under the second lock acquisition, which the atomicity
# rule's check/mutation pairing cannot see), and the 9 `key-linearity`
# sites from the dataflow tier (PR 20): deliberate key reuse for
# verified bit-identity (drafter host-vs-device parity, replay
# determinism tests) or fold_in-style per-lane derivation the linear
# model cannot prove. Bump ONLY with a justification comment at the
# new suppression site; never to paper over a lazy disable. The
# per-rule caps below pin each rule's count separately so a new
# suppression under one rule cannot hide behind slack freed up under
# another; the dataflow rules terminal-path and replay-taint are
# pinned at ZERO suppressions — their escapes are the `# discharges:`
# and `# replay-exempt:` annotations, not disables. --time-budget
# backs the "whole-tree lint stays interactive" contract (the shared
# walk index + AST-span comment scanner keep the full strict run
# around 4s on one CI core). The JSON report lands at
# $ORYX_LINT_REPORT as the CI artifact (findings, per-rule counts,
# suppression totals).
ORYX_LINT_REPORT=${ORYX_LINT_REPORT:-/tmp/oryxlint_report.json}
lint_args=(--strict --max-suppressions 41 --json-out "$ORYX_LINT_REPORT"
           --max-suppressions-per-rule key-linearity=9
           --max-suppressions-per-rule terminal-path=0
           --max-suppressions-per-rule replay-taint=0
           --time-budget 5.0)
if [ "${ORYX_LINT_CHANGED:-0}" != "0" ]; then
    lint_args+=(--changed-only)
fi
echo "running oryxlint (${lint_args[*]})"
if ! timeout -k 10 120 python scripts/run_oryxlint.py "${lint_args[@]}"; then
    echo "ORYXLINT FAILED (static analysis findings above)" >&2
    exit 1
fi
echo "oryxlint report artifact: $ORYX_LINT_REPORT"

# --- ROADMAP.md "Tier-1 verify", verbatim -----------------------------------
bash -c "set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 960 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=\${PIPESTATUS[0]}; echo DOTS_PASSED=\$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?\$' /tmp/_t1.log | tr -cd . | wc -c); exit \$rc"
rc=$?
# ----------------------------------------------------------------------------

dots=$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo "tier-1: $dots passed (baseline $BASELINE_DOTS, pytest rc=$rc)"
if [ "$dots" -lt "$BASELINE_DOTS" ]; then
    echo "TIER-1 REGRESSION: $dots < baseline $BASELINE_DOTS" >&2
    exit 1
fi
echo "tier-1 OK: no regression vs recorded baseline"

# --- concurrency suites under the runtime sanitizers -------------------------
# Second pass over the scheduler/containment suites with
# ORYX_LOCK_SANITIZER=1: every named lock is instrumented (ordering
# violations / guarded-field races raise at the faulty access, and the
# conftest fixture fails any test whose violations were swallowed by
# failure containment). This is the runtime proof the declared lock
# order in oryx_tpu/concurrency.py matches what the code actually does.
echo "checking concurrency suites under ORYX_LOCK_SANITIZER=1"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    ORYX_LOCK_SANITIZER=1 python -m pytest \
    tests/test_scheduler.py tests/test_containment.py \
    tests/test_trace.py tests/test_metrics_registry.py \
    tests/test_prefix_cache.py tests/test_lock_sanitizer.py \
    tests/test_router.py tests/test_ragged_attention.py \
    tests/test_speculative.py tests/test_pagemap.py \
    tests/test_forensics.py tests/test_device_time.py \
    tests/test_audit.py tests/test_numerics.py \
    tests/test_journal.py \
    -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly; then
    echo "LOCK SANITIZER SUITE FAILED (a concurrency violation above)" >&2
    exit 1
fi

# --- serving observability surface ------------------------------------------
# Boot a short-lived CPU server and verify /healthz + /readyz, /metrics
# (content type, oryx_serving_ name prefix, build_info gauge, HBM
# gauges) and the /debug flight recorder + trace endpoints are
# well-formed.
echo "checking serving endpoints (/healthz, /readyz, /metrics, /debug/*)"
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_serving_endpoints.py; then
    echo "SERVING ENDPOINT CHECK FAILED" >&2
    exit 1
fi

# --- output-quality observatory gate ----------------------------------------
# The ISSUE-14 acceptance bar: an --audit-sample-every 1 replica under a
# sequential greedy burst — every sampled request audits verdict=pass on
# the fp path, the /debug/audit ring reconciles exactly with
# oryx_audit_total{verdict=}, kind="audit" wide events validate against
# the schema registry, and live-traffic reply bytes + dispatch counters
# are identical to an unarmed twin (the auditor observes, never
# perturbs).
echo "checking output-quality observatory (--audit-smoke)"
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_serving_endpoints.py --audit-smoke; then
    echo "AUDIT OBSERVATORY CHECK FAILED" >&2
    exit 1
fi

# --- engine flight-recorder gate ---------------------------------------------
# The ISSUE-18 acceptance bar: a --journal armed replica under a
# sequential burst — /debug/journal well-formed and reconciled, the
# journal FILE replays offline byte-exactly (replay_journal.py:
# decision-for-decision stream equality + reply fingerprints), and
# live-traffic reply bytes + dispatch counters are identical to an
# unarmed twin (the journal observes, never perturbs).
echo "checking engine flight recorder (--journal-smoke)"
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_serving_endpoints.py --journal-smoke; then
    echo "JOURNAL FLIGHT-RECORDER CHECK FAILED" >&2
    exit 1
fi

# --- 2-replica router smoke --------------------------------------------------
# Two tiny replicas behind the prefix-affinity router
# (serve/router.py): the full endpoint gate runs against the ROUTER
# (merged /debug, replica-labeled /metrics/aggregate, upstream-TTFB
# quantiles), then a shared-prefix burst must show AFFINITY — one
# replica's oryx_serving_prefix_cache_hit_tokens_total dominates the
# fleet total.
echo "checking 2-replica router smoke (affinity + merged endpoints)"
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_serving_endpoints.py --router-smoke; then
    echo "ROUTER SMOKE FAILED" >&2
    exit 1
fi

# --- prefix-cache perf gate --------------------------------------------------
# Repeated-system-prompt workload through the continuous scheduler,
# cache off vs on: replies must stay bit-identical and prefill tokens
# computed must drop >= 2x (the PR-4 acceptance bar; TTFT is reported
# but not gated in smoke mode — wall clock on shared CI is noisy).
echo "checking prefix-cache perf (bench_prefix_cache.py --smoke)"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/bench_prefix_cache.py --smoke > /dev/null; then
    echo "PREFIX CACHE PERF CHECK FAILED" >&2
    exit 1
fi

# --- ragged paged-attention + speculation gate -------------------------------
# The fused one-dispatch engine path (--ragged) against the split
# path: dispatches/step must be EXACTLY 1 on the ragged engine (the
# oryx_serving_dispatches_total{kind=} counters are the proof), zero
# recompiles after warmup under recompile_watchdog (static dispatch
# shape across live-slot mixes), and replies byte-identical split vs
# ragged. The speculation cell (repetitive-text fixture through
# --speculate) additionally gates accepted-tokens/step > 1.5,
# dispatches/step still 1.0 (kind="spec" only) and byte parity vs the
# plain ragged engine.
echo "checking ragged paged attention (bench_paged_attention.py --smoke)"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/bench_paged_attention.py --smoke > /dev/null; then
    echo "RAGGED PAGED ATTENTION CHECK FAILED" >&2
    exit 1
fi

# --- chaos suite: fault injection + failure containment ----------------------
# Every named fault scenario (injected page-pool OOM, engine-thread
# crash, the same crash journaled + replayed offline bit-for-bit,
# hung dispatch vs deadline, mid-stream client disconnect,
# checkpoint-save failure) against a live tiny server: pool invariants
# hold, zero leaked pages/refcounts, /readyz returns to 200, and
# oryx_faults_injected_total reconciles against the injection schedule.
# Runs with the lock sanitizer armed: restart/drain/hung-dispatch are
# the rarely-trodden lock paths, and the suite fails on any ordering
# violation, race, or re-entrant scheduler._cond acquire it records.
echo "checking failure containment (chaos_suite.py, lock sanitizer armed)"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    ORYX_LOCK_SANITIZER=1 python scripts/chaos_suite.py; then
    echo "CHAOS SUITE FAILED (a fault escaped containment)" >&2
    exit 1
fi

# --- open-loop capacity harness ----------------------------------------------
# Seeded Poisson sweep against a self-booted tiny continuous-engine
# server with the SLO detectors armed: the report must be schema-valid,
# a saturation knee must exist, zero ttft_slo/queue_depth_slo firings
# at/below the knee, and every finished request must carry a complete
# cost ledger (prefill/cached tokens, decode steps, page-seconds).
echo "checking capacity harness (loadgen.py --smoke)"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/loadgen.py --smoke > /dev/null; then
    echo "LOADGEN CAPACITY CHECK FAILED" >&2
    exit 1
fi

# --- bench regression sentinel -----------------------------------------------
# The loadgen smoke above regenerated BENCH_loadgen.json; diff it (and
# BENCH_paged_attention.json) against the committed baselines/ with
# noise-aware per-metric-class tolerances. A moved knee, collapsed
# accepted-tokens/step, >1 dispatches/step or flipped byte parity
# fails CI with the offending series named; non-comparable runs
# (backend or sweep-config drift) are refused, not diffed. Refresh
# baselines deliberately with `bench_compare.py --update-baselines`.
# (Runs BEFORE the router sweep below, which rewrites the artifact
# with its router-flavored config.)
echo "checking bench regression sentinel (bench_compare.py --gate)"
if ! timeout -k 10 120 python scripts/bench_compare.py --gate; then
    echo "BENCH REGRESSION SENTINEL FAILED (see the verdict table)" >&2
    exit 1
fi

# --- router capacity harness -------------------------------------------------
# The same seeded sweep through a 2-replica prefix-affinity fleet:
# schema + knee + zero SLO firings below it (summed across replicas),
# per-replica goodput split recorded, router-level 503/retries
# classified apart from backend errors, and the sweep-wide affinity
# hit rate must clear 0.5 on the shared-prefix mix. (Knee-vs-single
# comparison is recorded in the report; gating it needs multi-core
# hosts — see docs/OBSERVABILITY.md.)
echo "checking router capacity harness (loadgen.py --smoke --router 2)"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/loadgen.py --smoke --router 2 > /dev/null; then
    echo "ROUTER LOADGEN CHECK FAILED" >&2
    exit 1
fi

# --- trainer telemetry exporter ---------------------------------------------
# Short CPU train with the /metrics exporter attached: /readyz must flip
# 503 -> 200 while the step loop runs, and the exposition must be
# well-formed (oryx_train_ prefix, no duplicate families, the
# loss/tokens_per_sec/mfu/goodput_ratio/hbm_live_bytes series present).
echo "checking trainer telemetry exporter (/metrics, /healthz, /readyz)"
if ! timeout -k 10 400 env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_train_telemetry.py; then
    echo "TRAIN TELEMETRY CHECK FAILED" >&2
    exit 1
fi
