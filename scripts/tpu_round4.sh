#!/usr/bin/env bash
# One-shot on-chip perf/validation agenda (run when the axon tunnel is
# up): default bench (throughput + 64/256-frame latency split), the
# three perf sweeps, and the smoke eval on the real chip. Each step is
# its own python process (the chip claim frees between steps); a dead
# tunnel surfaces as the bench supervisor's structured error, not a
# hang. A failed step does NOT abort the agenda — the tunnel flaps for
# hours at a time, and whichever steps do land are the deliverable.
# Exception: a failed pre-step tunnel probe DOES abort early (every
# remaining step would just burn its timeout on the dead RPC); the
# watcher (tunnel_watch.sh) retries the agenda and .ok markers skip the
# steps that already landed. Results land under $1 (default /tmp/r4_onchip).
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
OUT=${1:-/tmp/r4_onchip}
mkdir -p "$OUT" || exit 1

# Only python processes that can actually dial the chip matter: the axon
# plugin registers unless PALLAS_AXON_POOL_IPS is empty in that process's
# environment (CPU test runs export it empty and are harmless). A pid is
# cleared ONLY on positive evidence — readable environ with the var
# present and empty; an unreadable environ or an unset/nonempty var
# counts as a possible claimer (the box default exports it nonempty).
claimers=()
for dir in /proc/[0-9]*; do
  pid=${dir#/proc/}
  [ "$pid" = "$$" ] && continue
  # Interpreter detection by either signal: /proc/<pid>/exe catches
  # `pytest`/`ipython` entry points (comm says otherwise), comm covers
  # processes whose exe link is unreadable (other-user EACCES).
  comm=$(cat "$dir/comm" 2>/dev/null)
  exe=$(readlink "$dir/exe" 2>/dev/null) || exe=""
  case "$comm:$exe" in
    *python*|*pytest*|*ipython*) ;;
    *) continue ;;
  esac
  # The axon relay (/root/.relay.py) IS the tunnel — it runs with a
  # nonempty pool IP by design and must be up for any probe to succeed;
  # it is infrastructure, not a competing workload.
  cmdline=$(tr '\0' ' ' <"$dir/cmdline" 2>/dev/null) || cmdline=""
  case " $cmdline" in *" /root/.relay.py "*) continue ;; esac
  # Read the whole environ first (a pipe into grep -q can SIGPIPE tr
  # under pipefail); unreadable → empty → no positive evidence → flag.
  envtxt=$(tr '\0' '\n' <"$dir/environ" 2>/dev/null) || envtxt=""
  if ! grep -qx 'PALLAS_AXON_POOL_IPS=' <<<"$envtxt"; then
    # Exited between scan and read → cannot hold a claim; else flag.
    [ -e "$dir" ] && claimers+=("$pid")
  fi
done
if [ "${#claimers[@]}" -gt 0 ]; then
  echo "python process(es) ${claimers[*]} can hold the chip claim; aborting" >&2
  exit 1
fi
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}

fail=0
PROBE='import jax, jax.numpy as jnp; v = float(jax.device_get(jnp.sum(jnp.ones((256, 256), jnp.float32)))); assert v == 65536.0, v'
probe() {  # cheap tunnel check between steps: a dead tunnel must cost one
  # 2-min probe, not each remaining step's full timeout (the non-bench
  # steps have no supervisor; they hang on a dead RPC until killed).
  # stderr is kept so a persistent NON-tunnel failure (broken env,
  # import error) is diagnosable instead of reading as an eternal flap.
  timeout --kill-after=15 120 python -c "$PROBE" >/dev/null 2>"$OUT/probe.err"
}
step() {  # step <name> <timeout_s> <cmd...> — timeout: a hung tunnel must
  # cost one step, not the agenda (bench.py self-supervises, the rest
  # would block on a dead RPC forever). A step that already succeeded in
  # a previous run against the same OUT dir is skipped, so a watcher
  # retry after a mid-agenda tunnel death only repeats the missing steps.
  local name=$1 tmo=$2; shift 2
  if [ -e "$OUT/$name.ok" ]; then
    echo "== $name already ok; skipping =="
    return 0
  fi
  if ! probe; then
    echo "== $name: tunnel probe failed; aborting agenda (watcher retries) ==" >&2
    fail=1
    echo "== done early; results in $OUT (fail=$fail) =="
    exit "$fail"
  fi
  echo "== $name =="
  if timeout --kill-after=30 "$tmo" "$@" \
      2>"$OUT/$name.err" | tee "$OUT/$name.out"; then
    : >"$OUT/$name.ok"
  else
    echo "== $name FAILED (continuing; see $OUT/$name.err) ==" >&2
    fail=1
  fi
}

# 12600 > the supervisor's worst-case ladder (3 probes + 2 backoffs + up
# to 3 children at BENCH_TIMEOUT_S) so the outer kill can never preempt
# the structured {"error": ...} line.
step bench_default 12600 python bench.py
step tpu_validate 3600 python scripts/tpu_validate.py
# SWEEP_STATE_DIR banks per-config results (incl. deterministic OOMs)
# so watcher retries after a flap re-pay only the missing configs.
step sweep_loss_chunk 3600 env SWEEP_STATE_DIR="$OUT/sweep_state" \
  python scripts/bench_sweep.py loss_chunk
step sweep_fwd_blocks 3600 env SWEEP_STATE_DIR="$OUT/sweep_state" \
  python scripts/bench_sweep.py fwd_blocks
# 5 remat configs x 600 s per-config cap; 4500 leaves margin so the
# outer kill can't preempt the last config.
step sweep_remat 4500 env SWEEP_STATE_DIR="$OUT/sweep_state" \
  python scripts/bench_sweep.py remat
step sweep_batch 3600 env SWEEP_STATE_DIR="$OUT/sweep_state" \
  python scripts/bench_sweep.py batch
# Union of the per-sweep winners, full bench (throughput + latency):
# the evidence for flipping repo defaults, landed unattended. Gated on
# ALL sweeps having completed — a partial grid must not bank a stale
# "best" combination behind a .ok marker the watcher then skips.
if [ -e "$OUT/sweep_loss_chunk.ok" ] && [ -e "$OUT/sweep_fwd_blocks.ok" ] \
    && [ -e "$OUT/sweep_remat.ok" ] && [ -e "$OUT/sweep_batch.ok" ]; then
  step bench_best 12600 env SWEEP_STATE_DIR="$OUT/sweep_state" \
    python scripts/bench_best.py
else
  echo "== bench_best: sweeps incomplete; deferring to a watcher retry ==" >&2
  fail=1
fi
# Step named for its scoring mode so a stale marker from a generate-mode
# run can't skip the loglikelihood run.
step smoke_eval_ll 1800 python scripts/make_smoke_eval.py --out /tmp/smoke_tpu \
  --run --scoring loglikelihood --result "$OUT/smoke_result_tpu.json"
step components64 3600 env COMPONENT_FRAMES=64 python scripts/bench_components.py
step components256 3600 env COMPONENT_FRAMES=256 python scripts/bench_components.py
# Op-level device profile of the default bench step (the round-5 MFU
# optimization map); the xplane artifact stays under $OUT.
step trace 3600 env TRACE_DIR="$OUT/trace" python scripts/capture_trace.py

echo "== done; results in $OUT (fail=$fail) =="
exit "$fail"
