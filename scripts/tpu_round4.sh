#!/usr/bin/env bash
# One-shot on-chip perf/validation agenda (run when the axon tunnel is
# up): default bench (throughput + 64/256-frame latency split), the
# three perf sweeps, and the smoke eval on the real chip. Each step is
# its own python process (the chip claim frees between steps); a dead
# tunnel surfaces as the bench supervisor's structured error, not a
# hang. Results land under $1 (default /tmp/r4_onchip).
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/r4_onchip}
mkdir -p "$OUT"

if ps -eo pid,comm | awk '$2=="python"{found=1} END{exit !found}'; then
  echo "live python process holds the chip claim; aborting" >&2
  exit 1
fi
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}

echo "== bench (defaults) =="
python bench.py 2>"$OUT/bench_default.err" | tee "$OUT/bench_default.out"

echo "== sweep: loss_chunk =="
BENCH_NO_LATENCY=1 python scripts/bench_sweep.py loss_chunk \
  | tee "$OUT/sweep_loss_chunk.jsonl"

echo "== sweep: fwd_blocks =="
BENCH_NO_LATENCY=1 python scripts/bench_sweep.py fwd_blocks \
  | tee "$OUT/sweep_fwd_blocks.jsonl"

echo "== sweep: remat (incl attn_qkv) =="
BENCH_NO_LATENCY=1 python scripts/bench_sweep.py remat \
  | tee "$OUT/sweep_remat.jsonl"

echo "== smoke eval on chip =="
python scripts/make_smoke_eval.py --out /tmp/smoke_tpu --run \
  --result "$OUT/smoke_result_tpu.json" | tee "$OUT/smoke_eval.out"

echo "== done; results in $OUT =="
