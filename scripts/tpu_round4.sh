#!/usr/bin/env bash
# One-shot on-chip perf/validation agenda (run when the axon tunnel is
# up): default bench (throughput + 64/256-frame latency split), the
# three perf sweeps, and the smoke eval on the real chip. Each step is
# its own python process (the chip claim frees between steps); a dead
# tunnel surfaces as the bench supervisor's structured error, not a
# hang. A failed step does NOT abort the agenda — the tunnel flaps for
# hours at a time, and whichever steps do land are the deliverable.
# Results land under $1 (default /tmp/r4_onchip).
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
OUT=${1:-/tmp/r4_onchip}
mkdir -p "$OUT" || exit 1

# Only python processes that can actually dial the chip matter: the axon
# plugin registers unless PALLAS_AXON_POOL_IPS is empty in that process's
# environment (CPU test runs export it empty and are harmless). A pid is
# cleared ONLY on positive evidence — readable environ with the var
# present and empty; an unreadable environ or an unset/nonempty var
# counts as a possible claimer (the box default exports it nonempty).
claimers=()
for dir in /proc/[0-9]*; do
  pid=${dir#/proc/}
  [ "$pid" = "$$" ] && continue
  # Match on the interpreter binary, not comm: a `pytest`/`ipython`
  # entry point is still a python process that can dial the chip.
  case "$(readlink "$dir/exe" 2>/dev/null)" in
    *python*) ;;
    *) continue ;;
  esac
  if ! { tr '\0' '\n' <"$dir/environ" \
      | grep -qx 'PALLAS_AXON_POOL_IPS='; } 2>/dev/null; then
    # Exited between scan and read → cannot hold a claim; else flag.
    [ -e "$dir" ] && claimers+=("$pid")
  fi
done
if [ "${#claimers[@]}" -gt 0 ]; then
  echo "python process(es) ${claimers[*]} can hold the chip claim; aborting" >&2
  exit 1
fi
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}

fail=0
step() {  # step <name> <timeout_s> <cmd...> — timeout: a hung tunnel must
  # cost one step, not the agenda (bench.py self-supervises, the rest
  # would block on a dead RPC forever).
  local name=$1 tmo=$2; shift 2
  echo "== $name =="
  if ! timeout --kill-after=30 "$tmo" "$@" \
      2>"$OUT/$name.err" | tee "$OUT/$name.out"; then
    echo "== $name FAILED (continuing; see $OUT/$name.err) ==" >&2
    fail=1
  fi
}

# 12600 > the supervisor's worst-case ladder (3 probes + 2 backoffs + up
# to 3 children at BENCH_TIMEOUT_S) so the outer kill can never preempt
# the structured {"error": ...} line.
step bench_default 12600 python bench.py
step tpu_validate 3600 python scripts/tpu_validate.py
step sweep_loss_chunk 3600 python scripts/bench_sweep.py loss_chunk
step sweep_fwd_blocks 3600 python scripts/bench_sweep.py fwd_blocks
step sweep_remat 3600 python scripts/bench_sweep.py remat
step smoke_eval 1800 python scripts/make_smoke_eval.py --out /tmp/smoke_tpu \
  --run --result "$OUT/smoke_result_tpu.json"

echo "== done; results in $OUT (fail=$fail) =="
exit "$fail"
