"""AOT memory estimate of the bench-geometry train step per remat
policy — no TPU needed.

Lowers + compiles the full SFT step for the REAL bench geometry
(bench._bench_cfg's TPU branch) on one CPU device from
ShapeDtypeStructs (no 0.7B params materialized) and reads the
compiler's memory analysis. Argument bytes are exact arithmetic
(params + AdamW state + batch); temp bytes are the CPU compiler's
estimate — fusion details differ from TPU, but the DELTAS between remat
policies are dominated by the saved-residual buffers, which exist
identically on both backends. Use it to sanity-check whether a policy
plausibly fits the 16 GB v5e before spending chip time.

    python scripts/estimate_remat_memory.py [policy ...]
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GB = 1024**3


def one(policy: str, moment_dtype: str = "float32") -> dict:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from bench import _bench_cfg, _make_batch
    from oryx_tpu.models import oryx
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer

    geo, cfg, batch_size, seq_bucket, img_side = _bench_cfg(
        "tpu", 16 * GB
    )
    cfg = dataclasses.replace(
        cfg,
        attn_impl="xla",  # CPU-compilable; attention residuals same shape
        train=dataclasses.replace(
            cfg.train, remat=policy != "none", moment_dtype=moment_dtype,
            remat_policy=policy if policy != "none" else "block",
        ),
    )
    host = _make_batch(cfg, batch_size, seq_bucket, img_side)

    params_shape = jax.eval_shape(
        lambda: oryx.init_params(cfg, jax.random.key(0))
    )
    tx = make_optimizer(cfg.train, params_shape)
    opt_shape = jax.eval_shape(tx.init, params_shape)
    state_in = step_lib.TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=params_shape,
        opt_state=opt_shape,
    )
    batch = {
        k: jax.ShapeDtypeStruct((1, *v.shape), jnp.asarray(v).dtype)
        for k, v in host.items()
    }
    jit_step = jax.jit(
        step_lib.train_step_fn, static_argnames=("cfg", "tx"),
        donate_argnames=("state",),
    )
    compiled = jit_step.lower(state_in, batch, cfg=cfg, tx=tx).compile()
    ma = compiled.memory_analysis()
    overrides = {
        k: os.environ[k]
        for k in ("BENCH_BATCH", "BENCH_SEQ", "BENCH_LOSS_CHUNK")
        if os.environ.get(k)
    }
    return {
        "geometry": geo,
        "policy": policy,
        "moment_dtype": moment_dtype,
        # Inherited bench env overrides, recorded so a sweep-polluted
        # shell can't pass these numbers off as the default geometry.
        **({"env_overrides": overrides} if overrides else {}),
        "args_gb": round(ma.argument_size_in_bytes / GB, 2),
        "temp_gb": round(ma.temp_size_in_bytes / GB, 2),
        "total_gb": round(
            (ma.argument_size_in_bytes + ma.temp_size_in_bytes
             + ma.output_size_in_bytes - ma.alias_size_in_bytes) / GB, 2
        ),
    }


def main() -> None:
    cases = [("block", "float32"), ("attn", "float32"),
             ("attn_qkv", "float32"), ("attn_o", "float32"),
             ("attn_o", "bfloat16")]
    if len(sys.argv) > 1:
        # "policy" or "policy:moment_dtype" (e.g. attn_o:bfloat16).
        cases = [
            (p.split(":")[0], p.split(":")[1] if ":" in p else "float32")
            for p in sys.argv[1:]
        ]
    for policy, mdt in cases:
        print(json.dumps(one(policy, mdt)), flush=True)


if __name__ == "__main__":
    main()
