"""AOT memory estimate of the bench-geometry train step per remat
policy — no TPU chip needed.

Lowers + compiles the full SFT step for the REAL bench geometry
(bench._bench_cfg's TPU branch) from ShapeDtypeStructs (no 0.7B params
materialized) and reads the compiler's memory analysis.

Compile target (REMAT_EST_PLATFORM env, default "tpu"): with the local
libtpu, a v5e TOPOLOGY compile gives the actual XLA:TPU buffer
assignment — bf16 at true width, HBM capacity enforced at compile time
(RESOURCE_EXHAUSTED is captured and reported as {"oom": true} with the
required footprint) — for the REAL bench program including its Pallas
flash-attention kernels (which lower fine in a chipless topology
compile; pinned by tests/test_pallas_topology_compile.py). "cpu" falls
back to the one-CPU-device compile: no Pallas lowering there, so the
xla attention path substitutes (its larger backward transients make
those numbers conservative), and XLA:CPU's float normalization widens
bf16 buffers to fp32 — CPU temp bytes support policy DELTAS only, not
absolute fits.

    python scripts/estimate_remat_memory.py [policy[:moment_dtype] ...]
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GB = 1024**3


def _target_device():
    """One compile-target device: v5e topology (default) or local CPU."""
    import jax

    if os.environ.get("REMAT_EST_PLATFORM", "tpu") == "cpu":
        return jax.devices("cpu")[0], "cpu"
    from jax.experimental import topologies

    # Smallest valid v5e layout is 2x2 (host bounds); the single-device
    # program below targets one chip of it.
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    return topo.devices[0], "tpu_v5e_topology"


def one(policy: str, moment_dtype: str = "float32") -> dict:
    import dataclasses
    import re

    import jax
    import jax.numpy as jnp

    from bench import _bench_cfg, _make_batch
    from oryx_tpu.models import oryx
    from oryx_tpu.train import step as step_lib
    from oryx_tpu.train.optimizer import make_optimizer

    geo, cfg, batch_size, seq_bucket, img_side = _bench_cfg(
        "tpu", 16 * GB
    )
    # The TPU topology target compiles the bench cfg AS-IS — whatever
    # attention impl the real bench runs (Pallas lowers fine in a
    # chipless topology compile). Only the CPU fallback substitutes the
    # xla path (no Pallas lowering on CPU; its larger backward
    # transients make those numbers conservative).
    overrides_impl = (
        {"attn_impl": "xla"}
        if os.environ.get("REMAT_EST_PLATFORM", "tpu") == "cpu"
        else {}
    )
    cfg = dataclasses.replace(
        cfg,
        **overrides_impl,
        train=dataclasses.replace(
            cfg.train, remat=policy != "none", moment_dtype=moment_dtype,
            remat_policy=policy if policy != "none" else "block",
        ),
    )
    host = _make_batch(cfg, batch_size, seq_bucket, img_side)

    params_shape = jax.eval_shape(
        lambda: oryx.init_params(cfg, jax.random.key(0))
    )
    tx = make_optimizer(cfg.train, params_shape)
    opt_shape = jax.eval_shape(tx.init, params_shape)
    state_in = step_lib.TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=params_shape,
        opt_state=opt_shape,
    )
    dev, target = _target_device()
    shard = jax.sharding.SingleDeviceSharding(dev)
    state_in = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shard),
        state_in,
    )
    batch = {
        # canonicalize_dtype (x64-off int64->int32 etc.) without
        # materializing device arrays.
        k: jax.ShapeDtypeStruct(
            (1, *v.shape), jax.dtypes.canonicalize_dtype(v.dtype),
            sharding=shard,
        )
        for k, v in host.items()
    }
    jit_step = jax.jit(
        step_lib.train_step_fn, static_argnames=("cfg", "tx"),
        donate_argnames=("state",),
    )
    overrides = {
        k: os.environ[k]
        for k in ("BENCH_BATCH", "BENCH_SEQ", "BENCH_LOSS_CHUNK")
        if os.environ.get(k)
    }
    base = {
        "target": target,
        "geometry": geo,
        "policy": policy,
        "moment_dtype": moment_dtype,
        # Inherited bench env overrides, recorded so a sweep-polluted
        # shell can't pass these numbers off as the default geometry.
        **({"env_overrides": overrides} if overrides else {}),
    }
    try:
        compiled = jit_step.lower(state_in, batch, cfg=cfg, tx=tx).compile()
    except Exception as e:  # XLA:TPU enforces HBM at compile time.
        msg = str(e)
        if "RESOURCE_EXHAUSTED" not in msg:
            raise
        m = re.search(r"Used ([\d.]+)G of ([\d.]+)G hbm", msg)
        return {
            **base,
            "oom": True,
            "total_gb": float(m.group(1)) if m else None,
            "hbm_gb": float(m.group(2)) if m else None,
        }
    ma = compiled.memory_analysis()
    return {
        **base,
        "args_gb": round(ma.argument_size_in_bytes / GB, 2),
        "temp_gb": round(ma.temp_size_in_bytes / GB, 2),
        "total_gb": round(
            (ma.argument_size_in_bytes + ma.temp_size_in_bytes
             + ma.output_size_in_bytes - ma.alias_size_in_bytes) / GB, 2
        ),
    }


_CHILD_ENV = "ORYX_TPU_REMAT_EST_CHILD"


def main() -> None:
    if os.environ.get(_CHILD_ENV) != "1":
        # Re-exec in a clean CPU-client child: the caller's process may
        # otherwise initialize the default (axon TPU) backend just to
        # build ShapeDtypeStructs, contending for the single-process
        # chip claim. The TPU *compiler* target comes from the topology
        # API, not the client platform.
        import subprocess

        env = dict(os.environ)
        env[_CHILD_ENV] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
        sys.exit(subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env,
        ).returncode)

    cases = [("block", "float32"), ("attn", "float32"),
             ("attn_qkv", "float32"), ("attn_o", "float32"),
             ("attn_o", "bfloat16")]
    if len(sys.argv) > 1:
        # "policy" or "policy:moment_dtype" (e.g. attn_o:bfloat16).
        cases = [
            (p.split(":")[0], p.split(":")[1] if ":" in p else "float32")
            for p in sys.argv[1:]
        ]
    for policy, mdt in cases:
        print(json.dumps(one(policy, mdt)), flush=True)


if __name__ == "__main__":
    main()
