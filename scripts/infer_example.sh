#!/usr/bin/env bash
# Single-query inference (SURVEY.md §3.2): image or video QA.
#   MODEL=models/oryx7b-sft ./scripts/infer_example.sh --image cat.jpg \
#     --question "What is in this image?"
set -euo pipefail
cd "$(dirname "$0")/.."

MODEL=${MODEL:?path to oryx_tpu model dir}

python -m oryx_tpu.serve.cli --model-path "$MODEL" "$@"
