#!/usr/bin/env python
"""Ragged paged-attention bench: decode steps/s and DISPATCHES PER
ENGINE STEP, split engine vs the fused ragged engine, swept over
batch x context x prefill-chunk.

The fused path's whole claim is structural: a mixed prefill+decode
engine step pays ONE device dispatch (`paged_ragged_step`) instead of
an interleaved `_prefill_step` + `_step_chunk` pair, with a STATIC
dispatch shape across any live-slot mix. Both halves are measured, not
asserted:

  * dispatches/step — from the oryx_serving_dispatches_total{kind=}
    counters divided by decode beats (`chunks` counter). Ragged mode
    must be exactly 1.0; split mode pays 1 + prefills/beat.
  * zero recompiles after warmup — the measured phase runs under
    `recompile_watchdog` (analysis/sanitizers.py); ANY compile after
    the warmup workload is a failed shape-stability claim.
  * byte parity — every cell's replies are compared split vs ragged
    (the perf mode must not be a different model).

A SPECULATION cell rides the same harness (--speculate K): a
repetitive-text fixture through the spec engine vs the plain ragged
engine, gating accepted-tokens/step > 1.5, dispatches/step still
exactly 1.0 (kind="spec" only), zero recompiles after warmup, and
byte parity — "speculation changes nothing but speed", measured.

A FUSED MEGASTEP cell (--fuse-steps K; docs/DESIGN.md "Fused
multi-step decode") measures DISPATCHES PER TOKEN over the pure-decode
phase — plain ragged vs K=1 speculation vs the K-step megastep vs the
megastep with device-side draft speculation — from each run's decision
journal (pure-decode step entries only, so admission dispatches don't
launder the decode economics). Gates: the fused engine pays at most
1/K of plain ragged's dispatches-per-token (x 1+eps for ladder tails),
byte parity across EVERY mode, and zero recompiles after warmup.

Writes BENCH_paged_attention.json. On a CPU host the numbers are a
labeled cpu_proxy (structure claims — dispatch counts, recompiles,
parity — are backend-independent; steps/s is not).

    JAX_PLATFORMS=cpu python scripts/bench_paged_attention.py \
        [--batches 2,4] [--contexts 48,160] [--prefill-chunks 8,32] \
        [--max-new 8] [--json BENCH_paged_attention.json]
    python scripts/bench_paged_attention.py --smoke   # CI gate
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class _CharTokenizer:
    def encode(self, text, add_special_tokens=False):
        return [min(ord(c), 500) for c in text]

    def decode(self, ids, skip_special_tokens=True):
        return "".join(chr(i) for i in ids if 0 < i < 500)


def _prompts(batch: int, context: int) -> list[str]:
    """`batch` distinct prompts of ~`context` characters (distinct so
    the prefix cache can't collapse the sweep into one prefill)."""
    base = "please summarize the following numbers for me now "
    out = []
    for i in range(batch):
        body = (base + f"request {i} ") * (context // len(base) + 1)
        out.append(body[: max(8, context)])
    return out


DISPATCH_KINDS = (
    "ragged", "spec", "fused", "fused_spec", "prefill", "decode",
)


def _counter(metrics, kind: str) -> float:
    fam = metrics.registry.counter("dispatches_total", ("kind",))
    return fam.labels(kind=kind).value


def _accept_state(metrics) -> tuple[float, float]:
    """(sum, count) of the accepted-tokens-per-step histogram; two
    snapshots subtract into the measured-phase mean (the speculation
    headline, warmup excluded)."""
    from oryx_tpu.utils.metrics import parse_prom_histogram

    h = parse_prom_histogram(
        metrics.render(), "oryx_serving_accepted_tokens_per_step"
    )
    return (0.0, 0.0) if h is None else (h[3], float(h[2]))


def _pure_decode_stats(entries, after_step, lane_width):
    """(dispatches, tokens) over the PURE-DECODE step entries past the
    warmup watermark: rows == live_slots * lane_width means zero
    prefill lanes rode the dispatch, so admission traffic can't launder
    the decode economics; a megastep's K entries count as ONE dispatch
    (fused_j == 0) while every entry's accepted tokens count."""
    dispatches = 0
    tokens = 0
    for e in entries:
        if e.get("kind") != "step" or (e.get("step") or 0) <= after_step:
            continue
        live = e.get("live_slots") or 0
        if not live or e.get("rows") != live * lane_width:
            continue
        if e.get("fused_j") in (None, 0):
            dispatches += 1
        tokens += e.get("accepted_tokens") or 0
    return dispatches, tokens


def _run_mode(pipe, prompts, max_new, *, ragged, prefill_chunk,
              num_slots, watch, speculate=0, fuse_steps=1,
              drafter=None):
    """One measured cell: fresh scheduler, warmup workload (compiles
    the shape classes), then the measured burst under the recompile
    watchdog. Returns (result dict, replies)."""
    from oryx_tpu.analysis.sanitizers import recompile_watchdog
    from oryx_tpu.serve import journal as journal_lib
    from oryx_tpu.serve.scheduler import ContinuousScheduler
    from oryx_tpu.utils.metrics import ServingMetrics

    metrics = ServingMetrics()
    journal = journal_lib.DecisionJournal(None, keep=65536)
    sched = ContinuousScheduler(
        pipe, num_slots=num_slots, page_size=16, chunk=4, max_ctx=1024,
        metrics=metrics, autostart=False, prefill_chunk=prefill_chunk,
        ragged=ragged, speculate=speculate, fuse_steps=fuse_steps,
        journal=journal, **({"drafter": drafter} if drafter else {}),
    )
    sched.start()
    # Warmup: one short and one long admission so both shape classes
    # (prefill lanes present / absent) and the COW path compile; a
    # megastep engine additionally needs one request with K windows of
    # budget so its fused rung compiles before the measured burst.
    warm = [("warm up the compiler", 5), (prompts[0], 2)]
    if fuse_steps != 1:
        win = (1 + speculate) if speculate else 4
        warm.append(("warm the fused megastep rung", fuse_steps * win))
    for q, cap in warm:
        sched.submit({"question": q}, cap).result(timeout=600)
    stats = None
    t0 = time.monotonic()
    steps0 = max(
        (e.get("step") or 0 for e in journal.snapshot()
         if e.get("kind") == "step"),
        default=0,
    )
    dsteps0 = metrics.get("decode_steps_total")
    chunks0 = metrics.get("chunks")
    disp0 = {k: _counter(metrics, k) for k in DISPATCH_KINDS}
    acc0 = _accept_state(metrics)
    replies = []
    if watch:
        with recompile_watchdog(budget=1, action="record") as stats:
            handles = [
                sched.submit({"question": q}, max_new) for q in prompts
            ]
            results = [h.result(timeout=600) for h in handles]
    else:
        handles = [
            sched.submit({"question": q}, max_new) for q in prompts
        ]
        results = [h.result(timeout=600) for h in handles]
    replies = [r[0] for r in results]
    new_tokens = sum(r[2][1] for r in results)
    wall = time.monotonic() - t0
    beats = metrics.get("chunks") - chunks0
    disp = {
        k: _counter(metrics, k) - disp0[k] for k in DISPATCH_KINDS
    }
    acc1 = _accept_state(metrics)
    accept_mean = (
        (acc1[0] - acc0[0]) / (acc1[1] - acc0[1])
        if acc1[1] > acc0[1] else None
    )
    pd_disp, pd_tokens = _pure_decode_stats(
        journal.snapshot(), steps0, 1 + speculate
    )
    sched.close()
    journal.close()
    total_disp = sum(disp.values())
    out = {
        "wall_s": round(wall, 4),
        "decode_steps": metrics.get("decode_steps_total") - dsteps0,
        "decode_steps_per_s": round(
            (metrics.get("decode_steps_total") - dsteps0)
            / max(wall, 1e-9),
            2,
        ),
        "engine_steps": beats,
        "new_tokens": new_tokens,
        "dispatches": disp,
        "dispatches_per_step": round(total_disp / max(beats, 1), 4),
        "pure_decode": {
            "dispatches": pd_disp,
            "tokens": pd_tokens,
            "dispatches_per_token": (
                round(pd_disp / pd_tokens, 4) if pd_tokens else None
            ),
        },
        "recompiles_after_warmup": (
            dict(stats.counts) if stats is not None else None
        ),
    }
    if fuse_steps != 1:
        out["fuse_steps"] = fuse_steps
    if speculate:
        out["speculate"] = speculate
        out["accepted_tokens_per_step"] = (
            round(accept_mean, 4) if accept_mean is not None else None
        )
        out["draft_proposed"] = metrics.get("draft_proposed_total")
        out["draft_accepted"] = metrics.get("draft_accepted_total")
    return out, replies


def run(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batches", default="2,4")
    ap.add_argument("--contexts", default="48,160")
    ap.add_argument("--prefill-chunks", default="8,32")
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--num-slots", type=int, default=4)
    ap.add_argument("--json", default="BENCH_paged_attention.json")
    ap.add_argument(
        "--speculate", type=int, default=6, metavar="K",
        help="draft depth for the speculation cell (repetitive-text "
        "fixture, spec engine vs plain ragged; 0 skips the cell)",
    )
    ap.add_argument(
        "--fuse-steps", type=int, default=4, metavar="K",
        help="megastep depth for the fused-decode cell (dispatches "
        "per pure-decode token, fused vs spec vs plain ragged; "
        "1 skips the cell)",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="one tiny cell + hard gates (dispatches/step == 1 on the "
        "ragged path AND the speculative path, accepted-tokens/step "
        "> 1.5 on the repetitive fixture, zero recompiles after "
        "warmup, byte parity); wired into scripts/check_tier1.sh",
    )
    args = ap.parse_args(argv)
    if args.smoke:
        args.batches, args.contexts = "3", "64"
        args.prefill_chunks = "8"
        args.max_new = 6
        args.num_slots = 2
        args.json = None

    import jax

    from oryx_tpu import config as cfg_lib
    from oryx_tpu.models import oryx
    from oryx_tpu.serve.pipeline import OryxInference

    cfg = cfg_lib.oryx_tiny()
    params = oryx.init_params(cfg, jax.random.key(0))
    pipe = OryxInference(_CharTokenizer(), params, cfg)
    backend = jax.default_backend()

    cells = []
    failures = []
    for pc in [int(x) for x in args.prefill_chunks.split(",")]:
        for batch in [int(x) for x in args.batches.split(",")]:
            for ctx in [int(x) for x in args.contexts.split(",")]:
                prompts = _prompts(batch, ctx)
                split, r_split = _run_mode(
                    pipe, prompts, args.max_new, ragged=False,
                    prefill_chunk=pc, num_slots=args.num_slots,
                    watch=True,
                )
                ragg, r_ragg = _run_mode(
                    pipe, prompts, args.max_new, ragged=True,
                    prefill_chunk=pc, num_slots=args.num_slots,
                    watch=True,
                )
                parity = r_split == r_ragg
                cell = {
                    "batch": batch, "context_chars": ctx,
                    "prefill_chunk": pc,
                    "split": split, "ragged": ragg,
                    "replies_bit_identical": parity,
                }
                cells.append(cell)
                # Gates (structural claims; backend-independent).
                if not parity:
                    failures.append(f"cell {batch}x{ctx}x{pc}: replies differ")
                if ragg["dispatches_per_step"] != 1.0:
                    failures.append(
                        f"cell {batch}x{ctx}x{pc}: ragged paid "
                        f"{ragg['dispatches_per_step']} dispatches/step"
                    )
                if ragg["dispatches"]["prefill"] or ragg["dispatches"]["decode"]:
                    failures.append(
                        f"cell {batch}x{ctx}x{pc}: split-path dispatches "
                        f"leaked into ragged mode: {ragg['dispatches']}"
                    )
                for mode, res in (("split", split), ("ragged", ragg)):
                    rc = res["recompiles_after_warmup"]
                    if rc:
                        failures.append(
                            f"cell {batch}x{ctx}x{pc} {mode}: recompiled "
                            f"after warmup: {rc}"
                        )
    spec_cell = None
    if args.speculate:
        # Speculation cell (repetitive-text fixture): the spec engine's
        # whole claim is fewer SEQUENTIAL steps at one dispatch each —
        # gate accepted-tokens/step > 1.5, dispatches/step still 1.0
        # (kind="spec" only), zero recompiles after warmup, and byte
        # parity vs the plain ragged engine on the same prompts.
        rep = ("the quick brown fox jumps over the lazy dog " * 3).strip()
        prompts = [rep, rep + " again", rep + " and again"]
        # Long enough that the repetitive continuation dominates the
        # mean (the first few steps pay cold drafts); the fixture and
        # decode budget are fixed so the gate margin is stable.
        spec_new = max(args.max_new, 48)
        plain, r_plain = _run_mode(
            pipe, prompts, spec_new, ragged=True, prefill_chunk=8,
            num_slots=args.num_slots, watch=True,
        )
        spec, r_spec = _run_mode(
            pipe, prompts, spec_new, ragged=True, prefill_chunk=8,
            num_slots=args.num_slots, watch=True,
            speculate=args.speculate,
        )
        spec_cell = {
            "prompts": len(prompts), "max_new": spec_new,
            "speculate": args.speculate,
            "plain_ragged": plain, "spec": spec,
            "replies_bit_identical": r_plain == r_spec,
        }
        if r_plain != r_spec:
            failures.append("speculation cell: replies differ vs ragged")
        if spec["dispatches_per_step"] != 1.0:
            failures.append(
                f"speculation cell: {spec['dispatches_per_step']} "
                "dispatches/step (must stay 1.0)"
            )
        if (
            spec["dispatches"]["ragged"] or spec["dispatches"]["prefill"]
            or spec["dispatches"]["decode"]
        ):
            failures.append(
                "speculation cell: non-spec dispatch kinds leaked: "
                f"{spec['dispatches']}"
            )
        accept = spec.get("accepted_tokens_per_step")
        if accept is None or accept <= 1.5:
            failures.append(
                f"speculation cell: accepted-tokens/step {accept} "
                "(gate: > 1.5 on the repetitive fixture)"
            )
        if spec["recompiles_after_warmup"]:
            failures.append(
                "speculation cell: recompiled after warmup: "
                f"{spec['recompiles_after_warmup']}"
            )
    fused_cell = None
    if args.fuse_steps and args.fuse_steps > 1:
        # Fused megastep cell: dispatches per PURE-DECODE token across
        # the four engine modes on one fixture. The structural claim is
        # the K-fold dispatch cut — the megastep pays 1 dispatch where
        # the sequential engine pays K — with byte parity everywhere
        # and zero recompiles after warmup (each rung is one static
        # shape class). eps absorbs the K=1 ladder tail (a remaining
        # budget under K windows falls back to sequential dispatches).
        from oryx_tpu.models import generate as generate_lib

        # Solo resident, budget an exact multiple of K dispatch windows:
        # the pure-decode phase is megasteps end to end, so the measured
        # ratio IS the structural 1/K claim (a second resident staggers
        # admission and drags min-budget K=1 tails into the mean — the
        # engine-level mixes live in tests/test_fused_decode.py).
        K = args.fuse_steps
        rep = ("the quick brown fox jumps over the lazy dog " * 3).strip()
        prompts = [rep]
        fused_new = 12 * K
        mk_drafter = lambda: generate_lib.NeuralDrafter.init(  # noqa: E731
            cfg.llm.vocab_size, dim=8, window=8, seed=0
        )
        common = dict(
            ragged=True, prefill_chunk=32, num_slots=2, watch=True,
        )
        plain, r_plain = _run_mode(pipe, prompts, fused_new, **common)
        spec1, r_spec1 = _run_mode(
            pipe, prompts, fused_new, speculate=args.speculate or 6,
            **common,
        )
        fused, r_fused = _run_mode(
            pipe, prompts, fused_new, fuse_steps=K, **common,
        )
        fspec, r_fspec = _run_mode(
            pipe, prompts, fused_new, fuse_steps=K,
            speculate=args.speculate or 6, drafter=mk_drafter(),
            **common,
        )
        fused_cell = {
            "prompts": len(prompts), "max_new": fused_new,
            "fuse_steps": K,
            "plain_ragged": plain, "spec": spec1, "fused": fused,
            "fused_spec": fspec,
            "replies_bit_identical": (
                r_plain == r_spec1 == r_fused == r_fspec
            ),
        }
        if not fused_cell["replies_bit_identical"]:
            failures.append(
                "fused cell: replies differ across engine modes"
            )
        if not fused["dispatches"]["fused"]:
            failures.append("fused cell: no megastep dispatches paid")
        if not fspec["dispatches"]["fused_spec"]:
            failures.append(
                "fused cell: no speculative megastep dispatches paid"
            )
        plain_pt = plain["pure_decode"]["dispatches_per_token"]
        fused_pt = fused["pure_decode"]["dispatches_per_token"]
        eps = 0.15
        if plain_pt is None or fused_pt is None:
            failures.append(
                "fused cell: no pure-decode phase measured "
                f"(plain={plain_pt} fused={fused_pt})"
            )
        elif fused_pt > plain_pt / K * (1 + eps):
            failures.append(
                f"fused cell: {fused_pt} dispatches/token vs gate "
                f"{plain_pt}/{K}*(1+{eps}) = "
                f"{round(plain_pt / K * (1 + eps), 4)}"
            )
        for mode, res in (("fused", fused), ("fused_spec", fspec)):
            if res["recompiles_after_warmup"]:
                failures.append(
                    f"fused cell {mode}: recompiled after warmup: "
                    f"{res['recompiles_after_warmup']}"
                )
    out = {
        "bench": "paged_attention_ragged",
        "backend": backend if backend == "tpu" else "cpu_proxy",
        "geometry": {
            "num_slots": args.num_slots, "page_size": 16, "chunk": 4,
            "max_new": args.max_new,
        },
        "cells": cells,
        "speculation": spec_cell,
        "fused": fused_cell,
        "gates": {"failures": failures, "passed": not failures},
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    return out


def main(argv=None) -> int:
    out = run(argv)
    print(json.dumps(out, indent=2))
    if not out["gates"]["passed"]:
        print(
            "BENCH GATE FAILED: " + "; ".join(out["gates"]["failures"]),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
