// Native data-loader kernels: fused image preprocessing for the host
// pipeline.
//
// Reference parity: the reference's data loader leans on native code for
// its host-side hot path — torchvision/PIL-SIMD resize, torch tensor ops,
// DataLoader worker processes (SURVEY.md §3.1 "DataLoader worker procs
// decode images/video frames"). This library is the TPU-framework
// equivalent: one pass over the source image produces the normalized,
// patchified float32 patch rows that ops/packing.py lays out for the
// device, fanned out over a std::thread pool (no GIL, no per-image Python
// overhead, no intermediate resized image buffer).
//
// Semantics contract (tested against the numpy path in
// oryx_tpu/data/mm_utils.py):
//   * bilinear resize, align_corners=False:   src = (dst + 0.5)*S - 0.5,
//     edge-clamped taps, matching torch F.interpolate / mm_utils.
//   * normalize: (x/255 - mean) / std  for uint8 inputs.
//   * patchify: output row r = (gy*gw + gx) holds patch pixels in
//     (py, px, c) order — the order import_hf.import_siglip flattens the
//     HF conv kernel to (ops/packing.py patchify).
//
// C ABI only (ctypes-consumed; no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Taps {
  std::vector<int> lo, hi;
  std::vector<float> frac;
};

// Source taps for every destination index along one axis.
Taps make_taps(int dst, int src) {
  Taps t;
  t.lo.resize(dst);
  t.hi.resize(dst);
  t.frac.resize(dst);
  const float scale = static_cast<float>(src) / static_cast<float>(dst);
  for (int i = 0; i < dst; ++i) {
    float s = (static_cast<float>(i) + 0.5f) * scale - 0.5f;
    float f = std::floor(s);
    int lo = static_cast<int>(f);
    t.frac[i] = s - f;
    t.lo[i] = std::min(std::max(lo, 0), src - 1);
    t.hi[i] = std::min(std::max(lo + 1, 0), src - 1);
  }
  return t;
}

// One image: resize to (out_h, out_w), normalize, write patch rows.
//
// Separable, horizontal-first with a two-row cache — the naive
// per-pixel 4-tap gather with patch-scattered writes defeats
// auto-vectorization and measured 0.6x numpy's vectorized path per core
// on this repo's build box:
//   1. each NEEDED source row is horizontally resampled + normalized
//      once into a cached out_w*C row (the only gather pass; cached by
//      source row index, so upscale reuses rows and downscale touches
//      each source row at most once — cost scales with out_w, never W);
//   2. vertical 2-tap blend of the two cached rows, contiguous and
//      auto-vectorizable;
//   3. one contiguous memcpy per horizontal patch into patch layout.
// Normalization commutes with bilinear blending (both linear), so values
// match the previous kernel to fp rounding (tests pin 1e-4).
template <typename T>
void preprocess_one(const T* img, int H, int W, int C, int out_h, int out_w,
                    int patch, float mean, float inv_std, float px_scale,
                    float* out) {
  const Taps ty = make_taps(out_h, H);
  const Taps tx = make_taps(out_w, W);
  const int gw = out_w / patch;
  const int patch_dim = patch * patch * C;
  const long rowW = static_cast<long>(W) * C;
  const long rowO = static_cast<long>(out_w) * C;
  const float a = px_scale * inv_std;  // (v*px_scale - mean)*inv_std
  const float b = -mean * inv_std;     //   == v*a + b
  std::vector<float> cache[2] = {std::vector<float>(rowO),
                                 std::vector<float>(rowO)};
  int cached_src[2] = {-1, -1};
  // `protect` pins the slot holding the OTHER row this y needs: without
  // it, computing the hi row could evict the lo row's slot while the
  // caller still holds a pointer into it.
  auto hrow = [&](int src_y, int protect) -> const float* {
    for (int s = 0; s < 2; ++s) {
      if (cached_src[s] == src_y) return cache[s].data();
    }
    const int s = (cached_src[0] == protect) ? 1 : 0;
    const T* r = img + static_cast<long>(src_y) * rowW;
    float* d = cache[s].data();
    for (int x = 0; x < out_w; ++x) {
      const T* p0 = r + static_cast<long>(tx.lo[x]) * C;
      const T* p1 = r + static_cast<long>(tx.hi[x]) * C;
      const float fx = tx.frac[x];
      for (int c = 0; c < C; ++c) {
        const float v0 = static_cast<float>(p0[c]);
        const float v1 = static_cast<float>(p1[c]);
        d[static_cast<long>(x) * C + c] = (v0 + (v1 - v0) * fx) * a + b;
      }
    }
    cached_src[s] = src_y;
    return d;
  };
  std::vector<float> orow(rowO);
  for (int y = 0; y < out_h; ++y) {
    const float* r0 = hrow(ty.lo[y], -1);
    const float* r1 = hrow(ty.hi[y], ty.lo[y]);
    const float fy = ty.frac[y];
    for (long i = 0; i < rowO; ++i) {
      orow[i] = r0[i] + (r1[i] - r0[i]) * fy;
    }
    const int gy = y / patch, py = y % patch;
    for (int gx = 0; gx < gw; ++gx) {
      float* dst = out + static_cast<long>(gy * gw + gx) * patch_dim +
                   static_cast<long>(py) * patch * C;
      std::memcpy(dst, orow.data() + static_cast<long>(gx) * patch * C,
                  sizeof(float) * patch * C);
    }
  }
}

}  // namespace

extern "C" {

// Preprocess one image. dtype: 0 = uint8 (scaled by 1/255), 1 = float32
// (used as-is). out must hold (out_h/patch)*(out_w/patch)*patch*patch*C
// floats. Returns 0 on success, negative on bad arguments.
int oryx_preprocess_image(const void* img, int dtype, int H, int W, int C,
                          int out_h, int out_w, int patch, float mean,
                          float std, float* out) {
  if (!img || !out || H <= 0 || W <= 0 || C <= 0 || patch <= 0) return -1;
  if (out_h % patch != 0 || out_w % patch != 0) return -2;
  const float inv_std = 1.0f / std;
  if (dtype == 0) {
    preprocess_one(static_cast<const uint8_t*>(img), H, W, C, out_h, out_w,
                   patch, mean, inv_std, 1.0f / 255.0f, out);
  } else if (dtype == 1) {
    preprocess_one(static_cast<const float*>(img), H, W, C, out_h, out_w,
                   patch, mean, inv_std, 1.0f, out);
  } else {
    return -3;
  }
  return 0;
}

// Batch preprocess over a thread pool. Arrays are length n; outs[i] points
// at image i's patch-row destination (may alias disjoint slices of one
// packed buffer — ops/packing.py writes each image's rows contiguously).
// num_threads <= 0 uses the hardware concurrency. Returns 0 on success,
// else the first nonzero per-image status.
int oryx_batch_preprocess(int n, const void** imgs, const int* dtypes,
                          const int* Hs, const int* Ws, const int* Cs,
                          const int* out_hs, const int* out_ws, int patch,
                          float mean, float std, float** outs,
                          int num_threads) {
  if (n <= 0) return 0;
  if (num_threads <= 0) {
    num_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (num_threads <= 0) num_threads = 1;
  }
  num_threads = std::min(num_threads, n);
  std::atomic<int> next(0), status(0);
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      int rc = oryx_preprocess_image(imgs[i], dtypes[i], Hs[i], Ws[i], Cs[i],
                                     out_hs[i], out_ws[i], patch, mean, std,
                                     outs[i]);
      if (rc != 0) {
        int expected = 0;
        status.compare_exchange_strong(expected, rc);
      }
    }
  };
  if (num_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(num_threads);
    for (int t = 0; t < num_threads; ++t) threads.emplace_back(worker);
    for (auto& th : threads) th.join();
  }
  return status.load();
}

int oryx_loader_abi_version() { return 1; }

}  // extern "C"
