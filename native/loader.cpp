// Native data-loader kernels: fused image preprocessing for the host
// pipeline.
//
// Reference parity: the reference's data loader leans on native code for
// its host-side hot path — torchvision/PIL-SIMD resize, torch tensor ops,
// DataLoader worker processes (SURVEY.md §3.1 "DataLoader worker procs
// decode images/video frames"). This library is the TPU-framework
// equivalent: one pass over the source image produces the normalized,
// patchified float32 patch rows that ops/packing.py lays out for the
// device, fanned out over a std::thread pool (no GIL, no per-image Python
// overhead, no intermediate resized image buffer).
//
// Semantics contract (tested against the numpy path in
// oryx_tpu/data/mm_utils.py):
//   * bilinear resize, align_corners=False:   src = (dst + 0.5)*S - 0.5,
//     edge-clamped taps, matching torch F.interpolate / mm_utils.
//   * normalize: (x/255 - mean) / std  for uint8 inputs.
//   * patchify: output row r = (gy*gw + gx) holds patch pixels in
//     (py, px, c) order — the order import_hf.import_siglip flattens the
//     HF conv kernel to (ops/packing.py patchify).
//
// C ABI only (ctypes-consumed; no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

struct Taps {
  std::vector<int> lo, hi;
  std::vector<float> frac;
};

// Source taps for every destination index along one axis.
Taps make_taps(int dst, int src) {
  Taps t;
  t.lo.resize(dst);
  t.hi.resize(dst);
  t.frac.resize(dst);
  const float scale = static_cast<float>(src) / static_cast<float>(dst);
  for (int i = 0; i < dst; ++i) {
    float s = (static_cast<float>(i) + 0.5f) * scale - 0.5f;
    float f = std::floor(s);
    int lo = static_cast<int>(f);
    t.frac[i] = s - f;
    t.lo[i] = std::min(std::max(lo, 0), src - 1);
    t.hi[i] = std::min(std::max(lo + 1, 0), src - 1);
  }
  return t;
}

template <typename T>
inline float load_norm(const T* img, long idx, float scale, float mean,
                       float inv_std) {
  return (static_cast<float>(img[idx]) * scale - mean) * inv_std;
}

// One image: resize to (out_h, out_w), normalize, write patch rows.
template <typename T>
void preprocess_one(const T* img, int H, int W, int C, int out_h, int out_w,
                    int patch, float mean, float inv_std, float px_scale,
                    float* out) {
  const Taps ty = make_taps(out_h, H);
  const Taps tx = make_taps(out_w, W);
  const int gw = out_w / patch;
  const int patch_dim = patch * patch * C;
  const long rowW = static_cast<long>(W) * C;
  for (int y = 0; y < out_h; ++y) {
    const long y0 = ty.lo[y] * rowW, y1 = ty.hi[y] * rowW;
    const float fy = ty.frac[y];
    const int gy = y / patch, py = y % patch;
    for (int x = 0; x < out_w; ++x) {
      const long x0 = static_cast<long>(tx.lo[x]) * C;
      const long x1 = static_cast<long>(tx.hi[x]) * C;
      const float fx = tx.frac[x];
      const int gx = x / patch, pxi = x % patch;
      float* dst = out + static_cast<long>(gy * gw + gx) * patch_dim +
                   (static_cast<long>(py) * patch + pxi) * C;
      for (int c = 0; c < C; ++c) {
        const float tl = load_norm(img, y0 + x0 + c, px_scale, mean, inv_std);
        const float tr = load_norm(img, y0 + x1 + c, px_scale, mean, inv_std);
        const float bl = load_norm(img, y1 + x0 + c, px_scale, mean, inv_std);
        const float br = load_norm(img, y1 + x1 + c, px_scale, mean, inv_std);
        const float top = tl + (tr - tl) * fx;
        const float bot = bl + (br - bl) * fx;
        dst[c] = top + (bot - top) * fy;
      }
    }
  }
}

}  // namespace

extern "C" {

// Preprocess one image. dtype: 0 = uint8 (scaled by 1/255), 1 = float32
// (used as-is). out must hold (out_h/patch)*(out_w/patch)*patch*patch*C
// floats. Returns 0 on success, negative on bad arguments.
int oryx_preprocess_image(const void* img, int dtype, int H, int W, int C,
                          int out_h, int out_w, int patch, float mean,
                          float std, float* out) {
  if (!img || !out || H <= 0 || W <= 0 || C <= 0 || patch <= 0) return -1;
  if (out_h % patch != 0 || out_w % patch != 0) return -2;
  const float inv_std = 1.0f / std;
  if (dtype == 0) {
    preprocess_one(static_cast<const uint8_t*>(img), H, W, C, out_h, out_w,
                   patch, mean, inv_std, 1.0f / 255.0f, out);
  } else if (dtype == 1) {
    preprocess_one(static_cast<const float*>(img), H, W, C, out_h, out_w,
                   patch, mean, inv_std, 1.0f, out);
  } else {
    return -3;
  }
  return 0;
}

// Batch preprocess over a thread pool. Arrays are length n; outs[i] points
// at image i's patch-row destination (may alias disjoint slices of one
// packed buffer — ops/packing.py writes each image's rows contiguously).
// num_threads <= 0 uses the hardware concurrency. Returns 0 on success,
// else the first nonzero per-image status.
int oryx_batch_preprocess(int n, const void** imgs, const int* dtypes,
                          const int* Hs, const int* Ws, const int* Cs,
                          const int* out_hs, const int* out_ws, int patch,
                          float mean, float std, float** outs,
                          int num_threads) {
  if (n <= 0) return 0;
  if (num_threads <= 0) {
    num_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (num_threads <= 0) num_threads = 1;
  }
  num_threads = std::min(num_threads, n);
  std::atomic<int> next(0), status(0);
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      int rc = oryx_preprocess_image(imgs[i], dtypes[i], Hs[i], Ws[i], Cs[i],
                                     out_hs[i], out_ws[i], patch, mean, std,
                                     outs[i]);
      if (rc != 0) {
        int expected = 0;
        status.compare_exchange_strong(expected, rc);
      }
    }
  };
  if (num_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(num_threads);
    for (int t = 0; t < num_threads; ++t) threads.emplace_back(worker);
    for (auto& th : threads) th.join();
  }
  return status.load();
}

int oryx_loader_abi_version() { return 1; }

}  // extern "C"
